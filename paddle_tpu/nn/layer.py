"""nn.Layer — the module base class.

Reference: python/paddle/nn/layer/layers.py ``Layer`` (parameters/buffers/
sublayers, hooks, state_dict, train/eval). The TPU-native difference is only
in what a Parameter holds (a jax.Array) — the containment/protocol surface
matches the reference so model code ports 1:1.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod

from ..core.dtype import convert_dtype, to_jax_dtype
from ..tensor import Parameter, Tensor

__all__ = ["Layer"]

_default_dtype = ["float32"]


def set_default_dtype(d):
    _default_dtype[0] = convert_dtype(d).name


def get_default_dtype():
    return _default_dtype[0]


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype).name if dtype else get_default_dtype()
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction helpers ---------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        from . import initializer as I

        dtype = dtype or self._dtype
        init = default_initializer
        trainable = True
        name = None
        if attr is not None and attr is not False:
            from .param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                init = attr.initializer or init
                trainable = attr.trainable
                name = attr.name
            elif isinstance(attr, I.Initializer):
                init = attr
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        raw = init(shape, to_jax_dtype(dtype))
        p = Parameter(raw, name=name, trainable=trainable)
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return Tensor(jnp.zeros([], to_jax_dtype(dtype or self._dtype)), name=name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        keys = set(super().__dir__())
        for store in ("_parameters", "_buffers", "_sub_layers"):
            keys.update(self.__dict__.get(store, {}))
        return sorted(keys)

    # -- traversal ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit(".", 1)[-1]
            if leaf in self._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = dict(self.state_dict())
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            raw = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(raw.shape) != tuple(tgt._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {tuple(raw.shape)} vs "
                    f"model {tuple(tgt._value.shape)}"
                )
            tgt._set_value(raw.astype(tgt._value.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device -------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            jd = to_jax_dtype(dtype)
            for p in self.parameters():
                if _dtype_mod.is_float_raw(p._value.dtype):
                    p._set_value(p._value.astype(jd))
            for b in self.buffers():
                if b is not None and _dtype_mod.is_float_raw(b._value.dtype):
                    b._set_value(b._value.astype(jd))
            self._dtype = convert_dtype(dtype).name
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            sub = repr(l).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
