import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import Tensor


def test_simple_backward():
    x = paddle_tpu.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_backward():
    x = paddle_tpu.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle_tpu.exp(x)
    z = (y * 2).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.exp([1.0, 2.0]), rtol=1e-5)


def test_branching_accumulation():
    x = paddle_tpu.to_tensor([3.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_matmul_grad():
    a = paddle_tpu.to_tensor(np.random.rand(3, 4).astype(np.float32), stop_gradient=False)
    b = paddle_tpu.to_tensor(np.random.rand(4, 5).astype(np.float32), stop_gradient=False)
    out = paddle_tpu.matmul(a, b).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(), np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_grad_accumulate_across_backward():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_no_grad():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    with paddle_tpu.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_stop_gradient_blocks():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    assert z.stop_gradient


def test_retain_graph():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_double_backward_without_retain_raises():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_paddle_grad_api():
    x = paddle_tpu.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle_tpu.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-6)
    assert x.grad is None  # grad() must not pollute .grad


def test_hooks():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_multi_output_op_grad():
    x = paddle_tpu.to_tensor(np.array([[3.0, 1.0], [2.0, 4.0]], np.float32), stop_gradient=False)
    vals, idx = paddle_tpu.topk(x, k=1, axis=1)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0], [0, 1]])


def test_autograd_backward_api():
    x = paddle_tpu.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    paddle_tpu.autograd.backward([y])
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


def test_pylayer():
    class Double(paddle_tpu.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle_tpu.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(y.numpy(), [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_broadcast_grad():
    x = paddle_tpu.to_tensor(np.ones((3, 4), np.float32), stop_gradient=False)
    b = paddle_tpu.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    (x + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3, 3, 3, 3])
