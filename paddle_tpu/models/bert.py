"""BERT encoder family (reference fixture:
test/dygraph_to_static/bert_dygraph_model.py — BASELINE config 1 is
BERT-base dygraph_to_static single-chip).

TPU-first: the encoder reuses the framework's Transformer building blocks
(nn.modules.transformer) so the whole pretraining step traces into one XLA
program under jit.to_static; masked-LM uses dense gather on masked
positions (static shapes, MXU-friendly)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import ops
from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.modules.common import Dropout, Embedding, Linear
from ..nn.modules.norm import LayerNorm
from ..tensor import Tensor

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertPretrainingCriterion", "bert_tiny", "bert_base"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02


def bert_tiny(**kw):
    d = dict(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
             intermediate_size=256, max_position_embeddings=128)
    d.update(kw)
    return BertConfig(**d)


def bert_base(**kw):
    return BertConfig(**kw)


def _winit(cfg):
    from ..nn.initializer import Normal
    from ..nn.param_attr import ParamAttr

    return ParamAttr(initializer=Normal(0.0, cfg.initializer_range))


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size, weight_attr=_winit(cfg))
        self.position_embeddings = Embedding(cfg.max_position_embeddings, cfg.hidden_size, weight_attr=_winit(cfg))
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, cfg.hidden_size, weight_attr=_winit(cfg))
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = ops.expand(
                ops.unsqueeze(ops.arange(0, s, dtype="int64"), 0), list(input_ids.shape))
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertSelfAttention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.qkv = Linear(h, 3 * h, weight_attr=_winit(cfg))
        self.out = Linear(h, h, weight_attr=_winit(cfg))
        self.dropout = Dropout(cfg.hidden_dropout)
        self._cfg = cfg

    def forward(self, x, attn_mask=None):
        cfg = self._cfg
        b, s = x.shape[0], x.shape[1]
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        qkv = ops.reshape(self.qkv(x), [b, s, 3, nh, hd])
        q = ops.squeeze(ops.slice(qkv, [2], [0], [1]), 2)
        k = ops.squeeze(ops.slice(qkv, [2], [1], [2]), 2)
        v = ops.squeeze(ops.slice(qkv, [2], [2], [3]), 2)
        o = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=cfg.attention_dropout,
            is_causal=False, training=self.training)
        return self.dropout(self.out(ops.reshape(o, [b, s, nh * hd])))


class BertLayer(Layer):
    """Post-LN encoder block (BERT convention)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(cfg)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc1 = Linear(cfg.hidden_size, cfg.intermediate_size, weight_attr=_winit(cfg))
        self.fc2 = Linear(cfg.intermediate_size, cfg.hidden_size, weight_attr=_winit(cfg))
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.attention(x, attn_mask))
        y = self.fc2(F.gelu(self.fc1(x)))
        return self.ln2(x + self.dropout(y))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = [BertLayer(cfg) for _ in range(cfg.num_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", l)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size, weight_attr=_winit(cfg))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            m = ops.unsqueeze(ops.unsqueeze(attention_mask, 1), 1)
            attention_mask = (1.0 - m.astype("float32")) * -1e9
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for l in self.layers:
            h = l(h, attention_mask)
        pooled = ops.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForPretraining(Layer):
    """MLM + NSP heads (reference PretrainModelLayer)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.mlm_transform = Linear(cfg.hidden_size, cfg.hidden_size, weight_attr=_winit(cfg))
        self.mlm_ln = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.nsp_head = Linear(cfg.hidden_size, 2, weight_attr=_winit(cfg))
        self.config = cfg

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, masked_positions=None):
        h, pooled = self.bert(input_ids, token_type_ids, position_ids, attention_mask)
        if masked_positions is not None:
            # gather masked positions: [B, M, H]
            g = ops.take_along_axis(
                h, ops.unsqueeze(masked_positions, -1).astype("int64"), 1)
        else:
            g = h
        g = self.mlm_ln(F.gelu(self.mlm_transform(g)))
        w = self.bert.embeddings.word_embeddings.weight
        mlm_logits = ops.matmul(g, w, transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(Layer):
    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels=None,
                mlm_weights=None):
        mlm = F.cross_entropy(mlm_logits, mlm_labels, reduction="none")
        if mlm_weights is not None:
            w = mlm_weights.astype(mlm.dtype)
            mlm = ops.sum(mlm * w) / ops.clip(ops.sum(w), min=1.0)
        else:
            mlm = ops.mean(mlm)
        if nsp_labels is None:
            return mlm
        nsp = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp
