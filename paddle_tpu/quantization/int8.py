"""TRUE int8 execution backend (reference analog: the int8 compute
kernels behind quantization — paddle/phi/kernels/fusion/
fused_linear_int8 family and the inference engine's quantized ops; the
python QDQ pass in quantization/ptq.py only SIMULATES them).

TPU-native: the MXU multiplies int8 operands natively at double the
bf16 rate, so the real quantized path is one
``lax.dot_general(int8, int8, preferred_element_type=int32)`` with
per-output-channel weight scales and per-tensor activation scales
(calibrated static, or dynamic absmax) applied as a cheap epilogue —
no custom kernel needed, the compiler owns the tiling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..ops import dispatch
from ..ops._factory import ensure_tensor
from ..tensor import Tensor

__all__ = ["quantized_matmul", "Int8Linear"]


def quantized_matmul(x, w_int8, w_scale, bias=None, act_scale=None,
                     name=None):
    """y = dequant(int8(x) @ w_int8) — int32 accumulation on the MXU.

    x: float [..., K]; w_int8: int8 [K, N]; w_scale: float [N]
    (per-output-channel); act_scale: None -> dynamic per-tensor absmax
    quantization of x, else the calibrated static scale.  Inference
    path: the round/clip quantizer is not differentiated (use QAT's
    fake-quant for training).
    """
    x = ensure_tensor(x)
    w_int8 = ensure_tensor(w_int8)
    w_scale = ensure_tensor(w_scale)
    args = [x, w_int8, w_scale]
    if bias is not None:
        args.append(ensure_tensor(bias))

    def fn(xv, wq, ws, *b):
        if act_scale is not None:
            xs = jnp.asarray(act_scale, jnp.float32)
        else:
            xs = jnp.max(jnp.abs(xv)) / 127.0 + 1e-12
        xq = jnp.clip(jnp.round(xv / xs), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, wq, (((xv.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * xs * ws
        if b:
            out = out + b[0]
        return out

    return dispatch.apply_nondiff(fn, *args)


class Int8Linear(Layer):
    """Drop-in inference replacement for a calibrated Linear: weights
    stored AS int8 (4x smaller than fp32, feeding the MXU int8 path)
    with per-output-channel scales."""

    def __init__(self, linear, act_scale=None):
        super().__init__()
        w = np.asarray(linear.weight._value, np.float32)   # [in, out]
        scale = np.abs(w).max(axis=0) / 127.0 + 1e-12      # per out-chan
        wq = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        # registered as BUFFERS so the int8 weights and scales persist
        # through state_dict like any other model state
        self.register_buffer("weight_int8", Tensor(jnp.asarray(wq)))
        self.register_buffer(
            "w_scale", Tensor(jnp.asarray(scale.astype(np.float32))))
        self.bias = getattr(linear, "bias", None)
        self._act_scale = (float(act_scale) if act_scale is not None
                           else None)

    def forward(self, x):
        return quantized_matmul(x, self.weight_int8, self.w_scale,
                                bias=self.bias,
                                act_scale=self._act_scale)
