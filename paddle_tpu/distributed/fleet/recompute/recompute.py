"""Activation recomputation (reference: fleet/recompute/recompute.py:69
RecomputeFunction PyLayer — saves inputs, reruns forward in backward with
tracked RNG state).

TPU-native: ``jax.checkpoint`` (remat) IS this feature, applied at the jax
level so XLA schedules the recompute optimally; RNG replay is automatic
because our RNG is functional (key Tensors). The eager path uses a PyLayer
that reruns the function on backward — same semantics, engine-level.
"""
from __future__ import annotations

import jax

from ....autograd.py_layer import PyLayer
from ....ops import dispatch
from ....ops.random import default_generator
from ....tensor import Tensor


def recompute(function, *args, **kwargs):
    """reference recompute.py:334 ``recompute(function, *args)``."""
    use_reentrant = kwargs.pop("use_reentrant", True)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    if not dispatch.is_grad_enabled() or not any(
        not t.stop_gradient for t in tensor_args
    ):
        return function(*args, **kwargs)

    # snapshot RNG so the backward rerun sees identical dropout masks
    rng_snapshot = default_generator.get_state() if preserve_rng_state else None

    class _Recompute(PyLayer):
        @staticmethod
        def forward(ctx, *tensors):
            ctx.save_for_backward(*tensors)
            if rng_snapshot is not None:
                ctx.rng = Tensor(rng_snapshot._value)
            with dispatch.no_grad():
                out = function(*args, **kwargs)
            ctx.single = not isinstance(out, (tuple, list))
            return out

        @staticmethod
        def backward(ctx, *grads):
            saved = ctx.saved_tensor()
            # rerun forward WITH grad tracking on detached inputs
            detached = [Tensor(t._value, stop_gradient=t.stop_gradient) for t in saved]
            it = iter(detached)
            new_args = [next(it) if isinstance(a, Tensor) else a for a in args]
            if rng_snapshot is not None:
                keep = default_generator.get_state()
                default_generator.set_state(ctx.rng)
            with dispatch.enable_grad():
                out = function(*new_args, **kwargs)
            if rng_snapshot is not None:
                default_generator.set_state(keep)
            outs = [out] if not isinstance(out, (tuple, list)) else list(out)
            from ....autograd.engine import run_backward, grad as _grad

            diff_inputs = [t for t in detached if not t.stop_gradient]
            gs = _grad(
                [o for o in outs if not o.stop_gradient],
                diff_inputs,
                grad_outputs=[Tensor(g._value) for g, o in zip(grads, outs) if not o.stop_gradient],
                allow_unused=True,
            )
            gi = iter(gs)
            result = []
            for t in detached:
                if t.stop_gradient:
                    result.append(None)
                else:
                    result.append(next(gi))
            return tuple(result)

    return _Recompute.apply(*tensor_args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference recompute_sequential: chunked recompute over a Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    per = max(1, len(layers) // segments)

    def run_segment(lo, hi):
        def seg(x):
            for l in layers[lo:hi]:
                x = l(x)
            return x

        return seg

    x = args[0]
    for lo in range(0, len(layers), per):
        hi = min(lo + per, len(layers))
        x = recompute(run_segment(lo, hi), x, **kwargs)
    return x
