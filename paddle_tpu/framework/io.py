"""paddle.save / paddle.load analog.

Reference: python/paddle/framework/io.py:646 ``save`` / :888 ``load`` —
pickle-based nested state dicts with tensor→numpy conversion. Identical
design here: Tensors serialize as numpy arrays; load rehydrates to Tensors
on the current place.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.name)
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Tensor(jnp.asarray(obj.array))
        t.name = obj.name
        return t
    if isinstance(obj, dict):
        return {k: _from_serializable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_serializable(v, return_numpy) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ("array", "name")

    def __init__(self, array, name=None):
        self.array = array
        self.name = name


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_serializable(obj, return_numpy=return_numpy)
