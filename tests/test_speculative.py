"""Speculative serving: draft propose / fused verify / rollback-exact
page accounting (serving/speculative.py; ISSUE-15)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import serving
from paddle_tpu.models import (
    GPTForPretraining, GPTStackedForPretraining, gpt_tiny, truncated_draft,
)
from paddle_tpu.serving import (
    BlockAllocator, SamplingParams, ServingEngine, SpeculativeEngine,
)

ENG_KW = dict(num_slots=3, page_size=16, max_context=64,
              cache_dtype="float32")


def _model(stacked=False):
    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    cls = GPTStackedForPretraining if stacked else GPTForPretraining
    m = cls(cfg)
    m.eval()
    return m, cfg


def _prompts(cfg, lengths=(5, 18, 9, 26, 13), seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size, (s,)) for s in lengths]


# ---------------------------------------------------------------------------
# BlockAllocator speculative-reservation API
# ---------------------------------------------------------------------------

class TestSpecReservations:
    def test_reserve_commit_rollback_ledger(self):
        a = BlockAllocator(8)                       # 7 allocatable
        base = a.alloc(2)
        sp = a.reserve_spec(3)
        assert len(sp) == 3
        assert (a.used_pages, a.spec_pages, a.free_pages) == (2, 3, 2)
        assert a.used_pages + a.spec_pages + a.free_pages == a.capacity
        a.commit_spec(sp[:1])
        a.rollback_spec(sp[1:])
        assert (a.used_pages, a.spec_pages, a.free_pages) == (3, 0, 4)
        a.free(base + sp[:1])
        assert a.free_pages == a.capacity

    def test_reserve_all_or_nothing(self):
        a = BlockAllocator(4)
        assert a.reserve_spec(5) is None
        assert a.spec_pages == 0 and a.free_pages == 3

    def test_typed_misuse_raises(self):
        a = BlockAllocator(6)
        sp = a.reserve_spec(2)
        with pytest.raises(ValueError):
            a.free(sp)                   # spec pages are not allocations
        with pytest.raises(ValueError):
            a.commit_spec([4])           # never reserved
        a.rollback_spec(sp)
        with pytest.raises(ValueError):
            a.rollback_spec(sp)          # double rollback

    def test_spec_counts_against_free_list(self):
        a = BlockAllocator(5)
        a.reserve_spec(4)
        assert a.alloc(1) is None        # spec pages are really held


# ---------------------------------------------------------------------------
# greedy parity + acceptance + trace bounds
# ---------------------------------------------------------------------------

class TestGreedyParity:
    def test_same_model_draft_layered(self):
        m, cfg = _model()
        prompts = _prompts(cfg)
        ref = ServingEngine(m, **ENG_KW)
        want = ref.generate_batch(prompts, 7)
        ref.close()
        serving.reset_serve_trace_counts()
        eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
        got = eng.generate_batch(prompts, 7)
        for g, w in zip(got, want):
            assert np.array_equal(g, w)
        mets = eng.metrics()
        assert mets["spec_acceptance_rate"] == 1.0
        assert mets["spec_proposed_tokens"] > 0
        tc = serving.serve_trace_counts()
        assert tc["fused"] <= 2 and tc["draft"] <= 2, tc
        assert eng.allocator.used_pages == 0
        assert eng.draft.allocator.used_pages == 0
        assert eng.draft.allocator.spec_pages == 0
        eng.close()

    @pytest.mark.slow
    def test_matches_generate(self):
        m, cfg = _model()
        prompts = _prompts(cfg, lengths=(6, 14, 9))
        refs = [np.asarray(m.generate(
            pt.to_tensor(p[None, :], dtype="int64"), max_new_tokens=5,
            max_seq_len=64, cache_dtype="float32").numpy())[0]
            for p in prompts]
        eng = SpeculativeEngine(m, m, spec_k=4, **ENG_KW)
        got = eng.generate_batch(prompts, 5)
        for g, w in zip(got, refs):
            assert np.array_equal(g, w)
        eng.close()

    @pytest.mark.slow
    def test_truncated_draft_parity(self):
        m, cfg = _model()
        d = truncated_draft(m, 1)
        assert len(d.gpt.layers) == 1
        prompts = _prompts(cfg, lengths=(5, 18, 9))
        ref = ServingEngine(m, **ENG_KW)
        want = ref.generate_batch(prompts, 6)
        ref.close()
        eng = SpeculativeEngine(m, d, spec_k=3, **ENG_KW)
        got = eng.generate_batch(prompts, 6)
        for g, w in zip(got, want):
            assert np.array_equal(g, w)   # exact REGARDLESS of acceptance
        mets = eng.metrics()
        assert 0.0 <= mets["spec_acceptance_rate"] <= 1.0
        eng.close()

    @pytest.mark.slow
    def test_eos_truncates_accepted_run(self):
        m, cfg = _model()
        prompts = _prompts(cfg, lengths=(6, 11))
        ref = ServingEngine(m, **ENG_KW)
        r_ref = [ref.submit(p, 8, eos_token_id=int(t)) for p, t in
                 zip(prompts, (3, 7))]
        ref.run_until_idle()
        ref.close()
        eng = SpeculativeEngine(m, m, spec_k=4, **ENG_KW)
        r_got = [eng.submit(p, 8, eos_token_id=int(t)) for p, t in
                 zip(prompts, (3, 7))]
        eng.run_until_idle()
        for g, w in zip(r_got, r_ref):
            assert g.tokens == w.tokens
        assert eng.allocator.used_pages == 0
        eng.close()

    @pytest.mark.slow
    def test_same_model_draft_stacked(self):
        m, cfg = _model(stacked=True)
        prompts = _prompts(cfg, lengths=(5, 18, 9))
        ref = ServingEngine(m, **ENG_KW)
        want = ref.generate_batch(prompts, 6)
        ref.close()
        eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
        got = eng.generate_batch(prompts, 6)
        for g, w in zip(got, want):
            assert np.array_equal(g, w)
        assert eng.metrics()["spec_acceptance_rate"] == 1.0
        eng.close()


# ---------------------------------------------------------------------------
# sampling: leftover-distribution resampling preserves the target dist
# ---------------------------------------------------------------------------

class TestLeftoverResampling:
    def _dist_trial(self, p_logits, q_probs, k, trials, seed=0):
        """Empirical distribution of the FIRST emitted token when the
        draft proposes from q against target logits — across S=trials
        parallel slots in few dispatches."""
        from paddle_tpu.serving.speculative import _verify_tokens
        from paddle_tpu.tensor import to_tensor

        V = p_logits.shape[-1]
        pt.seed(seed)
        rng = np.random.RandomState(seed)
        counts = np.zeros(V)
        S = 256
        q = np.asarray(q_probs, np.float32)
        for _ in range(trials // S):
            # draft proposals drawn from q (host-side — the draft's role)
            d = np.stack([rng.choice(V, size=k, p=q) for _ in range(S)])
            lg = np.broadcast_to(p_logits, (S, k + 1, V)).copy()
            out, n_acc, fin = _verify_tokens(
                to_tensor(lg), to_tensor(d.astype(np.int32)),
                to_tensor(np.full((S,), k, np.int32)),
                to_tensor(np.ones((S,), np.float32)),
                to_tensor(np.ones((S,), np.float32)),
                to_tensor(np.zeros((S,), np.int32)),
                to_tensor(np.ones((S,), bool)),
                qprobs=[to_tensor(np.broadcast_to(q, (S, V)).copy())
                        for _ in range(k)])
            out = np.asarray(out.numpy())
            for s in range(S):
                counts[int(out[s, 0])] += 1
        return counts / counts.sum()

    def test_first_token_distribution_is_target(self):
        V, k = 8, 2
        rng = np.random.RandomState(3)
        p_logits = rng.randn(k + 1, V).astype(np.float32)
        q = rng.rand(V).astype(np.float32) + 0.1
        q /= q.sum()
        emp = self._dist_trial(p_logits, q, k, trials=4096)
        want = np.exp(p_logits[0]) / np.exp(p_logits[0]).sum()
        # 4096 samples: per-bucket std <= ~0.008; assert within 5 sigma
        assert np.abs(emp - want).max() < 0.05, (emp, want)

    @pytest.mark.slow
    def test_identical_draft_always_accepts(self):
        """q == p makes the accept probability exactly 1 — no resampling
        path ever fires, n_acc == k deterministically."""
        from paddle_tpu.serving.speculative import _verify_tokens
        from paddle_tpu.tensor import to_tensor

        V, k, S = 8, 3, 16
        rng = np.random.RandomState(5)
        lg = rng.randn(S, k + 1, V).astype(np.float32)
        p = np.exp(lg) / np.exp(lg).sum(-1, keepdims=True)
        pt.seed(0)
        # proposals sampled from p itself
        d = np.stack([[rng.choice(V, p=p[s, j]) for j in range(k)]
                      for s in range(S)]).astype(np.int32)
        out, n_acc, fin = _verify_tokens(
            to_tensor(lg), to_tensor(d),
            to_tensor(np.full((S,), k, np.int32)),
            to_tensor(np.ones((S,), np.float32)),
            to_tensor(np.ones((S,), np.float32)),
            to_tensor(np.zeros((S,), np.int32)),
            to_tensor(np.ones((S,), bool)),
            qprobs=[to_tensor(p[:, j]) for j in range(k)])
        assert (np.asarray(n_acc.numpy()) == k).all()
        assert np.array_equal(np.asarray(out.numpy())[:, :k], d)
        assert np.asarray(fin.numpy()).all()

    def test_dead_qrows_masked_per_slot(self):
        """A slot with n_draft BELOW the tick's max (incl. 0) must draw
        its bonus from the pure target row — the q rows of propose
        iterations it never joined are another slot's distribution and
        must be masked to zero, not subtracted (regression: unmasked
        q_ext rows skewed the emitted distribution for mixed-nd
        ticks)."""
        from paddle_tpu.serving.speculative import _verify_tokens
        from paddle_tpu.tensor import to_tensor

        V, k, S = 8, 2, 256
        rng = np.random.RandomState(11)
        row = rng.randn(V).astype(np.float32)
        lg = np.broadcast_to(row, (S, k + 1, V)).copy()
        garbage = rng.rand(S, V).astype(np.float32)
        garbage /= garbage.sum(-1, keepdims=True)
        pt.seed(4)
        counts = np.zeros(V)
        for _ in range(16):
            out, n_acc, _fin = _verify_tokens(
                to_tensor(lg),
                to_tensor(np.zeros((S, k), np.int32)),
                to_tensor(np.zeros((S,), np.int32)),      # n_draft = 0
                to_tensor(np.ones((S,), np.float32)),
                to_tensor(np.ones((S,), np.float32)),
                to_tensor(np.zeros((S,), np.int32)),
                to_tensor(np.ones((S,), bool)),
                qprobs=[to_tensor(garbage) for _ in range(k)])
            assert (np.asarray(n_acc.numpy()) == 0).all()
            for t in np.asarray(out.numpy())[:, 0]:
                counts[int(t)] += 1
        emp = counts / counts.sum()
        want = np.exp(row) / np.exp(row).sum()
        assert np.abs(emp - want).max() < 0.05, (emp, want)

    def test_greedy_chain_ignores_qprobs(self):
        from paddle_tpu.serving.speculative import _verify_tokens
        from paddle_tpu.tensor import to_tensor

        V, k, S = 8, 2, 4
        rng = np.random.RandomState(7)
        lg = rng.randn(S, k + 1, V).astype(np.float32)
        g = lg.argmax(-1)
        d = g[:, :k].astype(np.int32)            # propose the argmax chain
        out, n_acc, fin = _verify_tokens(
            to_tensor(lg), to_tensor(d),
            to_tensor(np.full((S,), k, np.int32)),
            to_tensor(np.ones((S,), np.float32)),
            to_tensor(np.ones((S,), np.float32)),
            to_tensor(np.zeros((S,), np.int32)),
            to_tensor(np.zeros((S,), bool)))     # greedy slots
        assert (np.asarray(n_acc.numpy()) == k).all()
        assert np.array_equal(np.asarray(out.numpy()), g)

    @pytest.mark.slow
    def test_sampling_requests_complete(self):
        m, cfg = _model()
        prompts = _prompts(cfg, lengths=(6, 12, 9))
        eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
        sp = SamplingParams(do_sample=True, temperature=0.9, top_k=50,
                            top_p=0.95)
        reqs = [eng.submit(p, 6, sampling=sp if i % 2 else None)
                for i, p in enumerate(prompts)]
        eng.run_until_idle()
        assert all(r.finished and len(r.tokens) == 6 for r in reqs)
        assert eng.allocator.used_pages == 0
        assert eng.draft.allocator.spec_pages == 0
        eng.close()


# ---------------------------------------------------------------------------
# accounting under degradation / faults / churn
# ---------------------------------------------------------------------------

class TestSpecAccounting:
    @pytest.mark.slow
    def test_draft_pool_exhaustion_degrades_not_corrupts(self):
        m, cfg = _model()
        prompts = _prompts(cfg)
        ref = ServingEngine(m, **ENG_KW)
        want = ref.generate_batch(prompts, 7)
        ref.close()
        # 3 draft pages for 3 slots needing up to 4 pages each: constant
        # spec-reservation pressure -> skips, never wrong output
        eng = SpeculativeEngine(m, m, spec_k=3, draft_num_pages=4,
                                **ENG_KW)
        got = eng.generate_batch(prompts, 7)
        for g, w in zip(got, want):
            assert np.array_equal(g, w)
        mets = eng.metrics()
        assert mets["spec_draft_skips"] > 0
        assert eng.draft.allocator.used_pages == 0
        assert eng.draft.allocator.spec_pages == 0
        assert eng.draft.allocator.free_pages == \
            eng.draft.allocator.capacity
        eng.close()

    def test_randomized_fault_schedules_drain_exact(self):
        from paddle_tpu.serving.faults import random_schedule

        m, cfg = _model()
        prompts = _prompts(cfg)
        for seed in (0,):   # seed sweep breadth lives in the serving gate
            rng = np.random.RandomState(seed)
            eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
            random_schedule(rng, horizon=25, n_faults=4,
                            num_slots=3).install(eng)
            reqs = [eng.submit(p, 6) for p in prompts]
            eng.run_until_idle(max_steps=3000)
            assert all(r.terminal for r in reqs)
            for alloc in (eng.allocator, eng.draft.allocator):
                assert alloc.used_pages == 0
                assert alloc.spec_pages == 0
                assert alloc.free_pages == alloc.capacity
            eng.close()

    @pytest.mark.slow
    def test_cancel_mid_flight_rolls_back_draft(self):
        m, cfg = _model()
        eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
        r1 = eng.submit(_prompts(cfg)[0], 20)
        r2 = eng.submit(_prompts(cfg)[1], 20)
        for _ in range(3):
            eng.step()
        r1.cancel()
        eng.run_until_idle()
        assert r1.state == serving.RequestState.CANCELLED
        assert r2.finished
        assert eng.draft.allocator.used_pages == 0
        assert eng.draft.allocator.spec_pages == 0
        eng.close()

    def test_multi_token_itl_convention(self):
        """Tokens accepted in one verify step share the step timestamp:
        the ITL histogram records one observation per emitted token after
        the first (zeros within a step — the documented convention)."""
        m, cfg = _model()
        eng = SpeculativeEngine(m, m, spec_k=3, **ENG_KW)
        reqs = [eng.submit(p, 7) for p in _prompts(cfg, lengths=(6, 11))]
        eng.run_until_idle()
        itl = eng.metrics()["slo"]["itl"]
        want = sum(len(r.tokens) - 1 for r in reqs)
        assert itl["count"] == want, (itl, want)
        hist = eng.metrics()["spec_accepted_per_step"]
        # one observation per harvested verify run (per decode slot per
        # step); with same-model acceptance the mean is spec_k except on
        # budget-clamped tail runs
        assert hist["count"] >= 1
        assert hist["max"] <= eng.spec_k
        eng.close()

    def test_metrics_surface(self):
        m, cfg = _model()
        eng = SpeculativeEngine(m, m, spec_k=2, **ENG_KW)
        eng.generate_batch(_prompts(cfg, lengths=(6,)), 4)
        mets = eng.metrics()
        for key in ("spec_proposed_tokens", "spec_accepted_tokens",
                    "spec_verify_steps", "spec_draft_steps",
                    "spec_acceptance_rate", "spec_accepted_per_step",
                    "draft_pages_used", "draft_spec_pages"):
            assert key in mets, key
        assert mets["spec_k"] == 2
        eng.close()

    def test_spec_k_validation(self):
        m, _cfg = _model()
        with pytest.raises(ValueError):
            SpeculativeEngine(m, m, spec_k=0, **ENG_KW)

    def test_vocab_mismatch_typed(self):
        m, _cfg = _model()
        cfg2 = gpt_tiny(vocab_size=512, hidden_dropout=0.0,
                        attention_dropout=0.0)
        d = GPTForPretraining(cfg2)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeEngine(m, d, spec_k=2, **ENG_KW)


@pytest.mark.slow
class TestShardedSpeculative:
    def test_dp_replica_speculation(self):
        """Replica-level composition: every dp replica runs its own
        SpeculativeEngine behind the placement scheduler."""
        import jax

        from paddle_tpu.serving import ShardedServingEngine

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        m, cfg = _model()
        prompts = _prompts(cfg)
        ref = ServingEngine(m, **ENG_KW)
        want = ref.generate_batch(prompts, 5)
        ref.close()

        def factory(model, mesh, index, **kw):
            return SpeculativeEngine(model, model, spec_k=3, mesh=mesh,
                                     **kw)

        eng = ShardedServingEngine(m, dp=2, mp=1, engine_factory=factory,
                                   **ENG_KW)
        try:
            got = eng.generate_batch(prompts, 5)
            for g, w in zip(got, want):
                assert np.array_equal(g, w)
            for rep in eng.replicas:
                assert rep.metrics()["spec_acceptance_rate"] == 1.0
                assert rep.allocator.used_pages == 0
                assert rep.draft.allocator.used_pages == 0
        finally:
            eng.close()
