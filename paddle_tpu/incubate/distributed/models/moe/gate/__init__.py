"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py)."""
from .base_gate import BaseGate  # noqa: F401
from .naive_gate import NaiveGate  # noqa: F401
from .gshard_gate import GShardGate  # noqa: F401
from .switch_gate import SwitchGate  # noqa: F401
