"""paddle.geometric parity: segment reductions + graph message passing.

Reference: python/paddle/geometric/math.py (segment_sum/mean/max/min over
custom segment_pool CUDA kernels) and message_passing/send_recv.py
(send_u_recv / send_ue_recv / send_uv over graph_send_recv ops).

TPU-native redesign: all of these are gather/segment-reduce patterns that
XLA compiles well from ``jax.ops.segment_*`` — no custom kernels.  One
deliberate divergence: under a jit trace the output row count must be
static, so ``out_size`` (reference: optional) is REQUIRED when tracing;
eager calls infer it from the indices like the reference does.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dispatch
from ..ops._factory import ensure_tensor
from ..tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
]


def _n_segments(ids_t, out_size):
    if out_size is not None:
        return int(out_size)
    raw = ids_t._value
    if isinstance(raw, jax.core.Tracer):
        raise ValueError(
            "geometric ops need a static output size under jit: pass "
            "out_size=N (the number of segments/nodes)")
    return int(np.asarray(raw).max()) + 1 if raw.size else 0


def _reduce(msg, ids, n, reduce_op):
    """Segment-reduce ``msg`` by ``ids`` into ``n`` rows.  Shared by the
    segment_* API and the message-passing ops; empty segments yield 0
    (reference behavior) rather than jax's +/-inf identities."""
    if reduce_op == "sum":
        return jax.ops.segment_sum(msg, ids, num_segments=n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(ids, msg.dtype), ids,
                                num_segments=n)
        return s / jnp.reshape(jnp.maximum(c, 1),
                               (-1,) + (1,) * (msg.ndim - 1))
    red = jax.ops.segment_max if reduce_op == "max" else jax.ops.segment_min
    out = red(msg, ids, num_segments=n)
    return jnp.where(jnp.isfinite(out), out, jnp.zeros_like(out))


def _segment(op_name, reduce_op, data, segment_ids, out_size=None, name=None):
    data = ensure_tensor(data)
    ids = ensure_tensor(segment_ids)
    n = _n_segments(ids, out_size)

    def raw(d, i):
        return _reduce(d, i, n, reduce_op)

    return dispatch.apply(raw, data, ids, op_name=op_name)


def segment_sum(data, segment_ids, out_size=None, name=None):
    return _segment("segment_sum", "sum", data, segment_ids, out_size, name)


def segment_mean(data, segment_ids, out_size=None, name=None):
    return _segment("segment_mean", "mean", data, segment_ids, out_size, name)


def segment_max(data, segment_ids, out_size=None, name=None):
    return _segment("segment_max", "max", data, segment_ids, out_size, name)


def segment_min(data, segment_ids, out_size=None, name=None):
    return _segment("segment_min", "min", data, segment_ids, out_size, name)


_REDUCERS = {"sum": segment_sum, "mean": segment_mean,
             "max": segment_max, "min": segment_min}

_MESSAGE_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges, reduce at dst
    (reference send_recv.py send_u_recv / graph_send_recv op)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {list(_REDUCERS)}")
    x = ensure_tensor(x)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)
    n = _n_segments(dst, out_size)

    def raw(xv, sv, dv):
        return _reduce(jnp.take(xv, sv, axis=0), dv, n, reduce_op)

    return dispatch.apply(raw, x, src, dst, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce at dst."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"message_op must be one of {list(_MESSAGE_OPS)}")
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {list(_REDUCERS)}")
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)
    n = _n_segments(dst, out_size)
    mop = _MESSAGE_OPS[message_op]

    def raw(xv, yv, sv, dv):
        return _reduce(mop(jnp.take(xv, sv, axis=0), yv), dv, n, reduce_op)

    return dispatch.apply(raw, x, y, src, dst, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst] — no reduction
    (reference send_uv / graph_send_uv op)."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"message_op must be one of {list(_MESSAGE_OPS)}")
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)
    mop = _MESSAGE_OPS[message_op]

    def raw(xv, yv, sv, dv):
        return mop(jnp.take(xv, sv, axis=0), jnp.take(yv, dv, axis=0))

    return dispatch.apply(raw, x, y, src, dst, op_name="send_uv")
