"""Profiler (reference: python/paddle/profiler/profiler.py:340 over the C++
host/CUPTI tracers, N36). TPU-native: delegates to the XLA/TPU profiler
(jax.profiler) which captures host + device (TensorCore) timelines into
TensorBoard/trace-viewer format — the direct analog of the reference's
chrome-trace export."""
from __future__ import annotations

import os
import time
from contextlib import contextmanager
from enum import Enum

import jax


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1  # kept for API compat; maps to the TPU device timeline
    TPU = 2


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= period * repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._log_dir = dir_name

    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._log_dir = "./profiler_log"
        self._timer_only = timer_only
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._running = False
        self._step = 0
        self._step_times = []
        self._t0 = None

    def start(self):
        if self._on_trace_ready:
            self._on_trace_ready(self)
        if not self._timer_only:
            os.makedirs(self._log_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._log_dir)
                self._running = True
            except Exception:
                self._running = False
        self._t0 = time.perf_counter()

    def stop(self):
        if self._running:
            jax.profiler.stop_trace()
            self._running = False

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        arr = np.asarray(self._step_times[-10:])
        return f"avg step {arr.mean()*1000:.2f} ms (last {len(arr)})"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        print(self.step_info())

    def export(self, path, format="json"):  # noqa: A002
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Annotated range (reference: paddle.profiler.RecordEvent over
    platform/profiler RecordEvent) — maps to jax.profiler.TraceAnnotation."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


@contextmanager
def profile_annotation(name):
    with jax.profiler.TraceAnnotation(name):
        yield
