"""Train-path MFU push: fused donated train step, async device input
pipeline, bf16 params with fp32 master weights, measured remat-policy
search (docs/training_perf.md).

Invariants pinned here:
- FusedTrainStep compiles EXACTLY one program per input signature and
  every step is one compiled dispatch;
- fp32 mode through FusedTrainStep is BITWISE the legacy inline
  jit.to_static step (this PR must not move fp32 numerics);
- the bf16+master regime's fp32 masters track the fp32 reference within
  bf16-expected tolerance, and masters survive state_dict /
  CheckpointManager round-trips bitwise (the PR 4 resume invariant
  extended to multi_precision);
- the traced GradScaler protocol skips non-finite steps without touching
  any optimizer state and drives the dynamic scale as traced state;
- grouped remat (recompute_interval k > 1 on the stacked scan) is
  numerically identical to per-block remat;
- the DataLoader prefetch window clamps to >= 1 at num_workers == 0;
- DevicePrefetcher preserves order, accounts stalls, propagates errors;
- the autotune train_remat search space enumerates/validates/dispatches
  under the shared table discipline.
"""
import hashlib
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io import DataLoader, DevicePrefetcher
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.models import GPTStackedForPretraining, gpt_tiny
from paddle_tpu.optimizer import FusedTrainStep


def _batch(cfg, seed=1, b=2, s=16):
    rng = np.random.RandomState(seed)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)), dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)),
                          dtype="int64")
    return ids, labels


def _build(seed=0, regime="fp32", interval=1, policy=None, grad_clip=None):
    pt.seed(seed)
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0,
                   recompute_interval=interval, recompute_policy=policy)
    model = GPTStackedForPretraining(cfg)
    if regime in ("bf16", "master"):
        pt.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters(),
                             multi_precision=regime != "bf16",
                             grad_clip=grad_clip)
    return cfg, model, opt


def _param_sha(model) -> str:
    h = hashlib.sha256()
    for name, t in sorted(model.state_dict().items()):
        h.update(name.encode())
        h.update(np.asarray(t._value).tobytes())
    return h.hexdigest()


class TestFusedTrainStep:
    def test_compiles_exactly_once(self):
        """Trace-counter invariant: N same-shape steps = 1 program,
        N compiled dispatches."""
        cfg, model, opt = _build()
        step = FusedTrainStep(lambda i, l: model(i, labels=l), opt)
        ids, labels = _batch(cfg)
        for _ in range(4):
            loss = step(ids, labels)
        assert np.isfinite(float(loss))
        assert step.program_count == 1
        assert step.dispatch_count == 4
        assert step.last_step_applied

    def test_fp32_bitwise_vs_legacy_inline_step(self):
        """fp32 mode through FusedTrainStep is BITWISE the hand-rolled
        jit.to_static loss.backward(); opt.step() wrapper."""
        cfg, m1, o1 = _build(seed=7)
        ids, labels = _batch(cfg, seed=3)

        @pt.jit.to_static
        def legacy(ids, labels):
            loss = m1(ids, labels=labels)
            loss.backward()
            o1.step()
            o1.clear_grad()
            return loss

        ref = [float(legacy(ids, labels)) for _ in range(4)]
        ref_sha = _param_sha(m1)

        cfg, m2, o2 = _build(seed=7)
        ids, labels = _batch(cfg, seed=3)
        step = FusedTrainStep(lambda i, l: m2(i, labels=l), o2)
        got = [float(step(ids, labels)) for _ in range(4)]
        assert got == ref  # exact float equality
        assert _param_sha(m2) == ref_sha

    def test_master_weights_track_fp32_reference(self):
        """bf16 params + fp32 masters: the update runs on the masters, so
        the loss curve and the master values track the fp32 reference
        within bf16-forward-noise tolerance (the pure-bf16 regime drifts
        much further — that is the regime gap masters close)."""
        cfg, mf, of = _build(seed=11, regime="fp32")
        ids, labels = _batch(cfg, seed=5)
        sf = FusedTrainStep(lambda i, l: mf(i, labels=l), of)
        ref = [float(sf(ids, labels)) for _ in range(5)]

        cfg, mm, om = _build(seed=11, regime="master")
        ids, labels = _batch(cfg, seed=5)
        sm = FusedTrainStep(lambda i, l: mm(i, labels=l), om,
                            amp_level="O1", amp_dtype="bfloat16")
        got = [float(sm(ids, labels)) for _ in range(5)]
        # bf16 forward noise bounds the loss gap; the curve must not drift
        np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)
        # every master stays close to its fp32-reference counterpart (the
        # parameter lists build in the same order under the same seed)
        n_masters = 0
        for p_ref, p in zip(of._parameter_list, om._parameter_list):
            master = om._master.get(id(p))
            if master is None:
                continue
            n_masters += 1
            assert master._value.dtype == np.float32
            np.testing.assert_allclose(
                np.asarray(master._value), np.asarray(p_ref._value),
                atol=1e-2, rtol=0.2)
        assert n_masters > 0  # bf16 params actually have masters

    def test_traced_scaler_skips_nonfinite_step(self):
        """An overflowing scaled grad leaves params/moments/masters/scale
        counters consistent: params bitwise-unchanged, scale decayed; the
        next finite steps apply and regrow the scale — all without a host
        sync inside the step."""
        from paddle_tpu.tensor import Parameter
        import jax.numpy as jnp

        p = Parameter(jnp.ones((4, 4), jnp.float16))
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[p])
        scaler = pt.amp.GradScaler(enable=True, init_loss_scaling=2.0 ** 14,
                                   incr_every_n_steps=2,
                                   decr_every_n_nan_or_inf=1)
        step = FusedTrainStep(lambda x: (p * x).astype("float32").sum(),
                              opt, scaler=scaler)
        x_ok = pt.to_tensor(np.full((4, 4), 0.01, np.float16))
        x_bad = pt.to_tensor(np.full((4, 4), np.float16(60000)))
        before = np.asarray(p._value).copy()
        step(x_bad)  # scaled grad overflows fp16
        assert not step.last_step_applied
        assert np.array_equal(before, np.asarray(p._value))
        assert float(np.asarray(scaler._scale._value)) == 2.0 ** 13
        step(x_ok)
        assert step.last_step_applied
        assert not np.array_equal(before, np.asarray(p._value))
        step(x_ok)  # second consecutive good step -> scale grows
        assert float(np.asarray(scaler._scale._value)) == 2.0 ** 14
        assert step.program_count == 1  # one program serves all of it

    def test_rejects_unknown_amp_level(self):
        cfg, model, opt = _build()
        with pytest.raises(ValueError):
            FusedTrainStep(lambda i, l: model(i, labels=l), opt,
                           amp_level="O2")


class TestMasterWeightCheckpoint:
    def test_state_dict_carries_masters(self):
        cfg, model, opt = _build(regime="master")
        ids, labels = _batch(cfg)
        step = FusedTrainStep(lambda i, l: model(i, labels=l), opt,
                              amp_level="O1")
        float(step(ids, labels))
        sd = opt.state_dict()
        masters = [k for k in sd if k.startswith("master_")]
        assert masters
        # restore into a fresh optimizer: masters land bitwise
        cfg2, m2, o2 = _build(seed=123, regime="master")
        o2.set_state_dict(sd)
        for i, (p, p2) in enumerate(zip(opt._parameter_list,
                                        o2._parameter_list)):
            m, m2_ = opt._master.get(id(p)), o2._master.get(id(p2))
            if m is not None:
                assert m2_ is not None
                assert np.array_equal(np.asarray(m._value),
                                      np.asarray(m2_._value))

    def test_master_resume_bitwise(self, tmp_path):
        """train(4) == train(2); checkpoint through CheckpointManager;
        restore into a FRESH model; train(2) — bitwise (PR 4 invariant
        extended across fp32 master weights)."""
        from paddle_tpu.checkpoint import CheckpointManager, TrainState

        def setup(seed):
            cfg, model, opt = _build(seed=seed, regime="master")
            step = FusedTrainStep(lambda i, l: model(i, labels=l), opt,
                                  amp_level="O1")
            ids, labels = _batch(cfg, seed=9)
            return model, opt, step, ids, labels

        m, o, s, ids, labels = setup(0)
        ref = [float(s(ids, labels)) for _ in range(4)]
        ref_sha = _param_sha(m)

        m1, o1, s1, ids, labels = setup(0)
        pre = [float(s1(ids, labels)) for _ in range(2)]
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(TrainState(m1, o1).capture(position={"step": 2}), step=2)
        mgr.wait()

        m2, o2, s2, ids, labels = setup(999)  # different init
        tree, _ = mgr.restore()
        TrainState(m2, o2).restore(tree)
        post = [float(s2(ids, labels)) for _ in range(2)]
        assert pre == ref[:2]
        assert post == ref[2:]  # bitwise resume incl. masters
        assert _param_sha(m2) == ref_sha


class TestRematInterval:
    @pytest.mark.parametrize("interval,policy", [(2, "full"), (2, "dots"),
                                                 (1, "dots")])
    def test_grouped_remat_numeric_parity(self, interval, policy):
        """Grouped remat boundaries change memory, never math: losses are
        exactly the per-block remat run's."""
        def run(k, pol):
            cfg, model, opt = _build(seed=4, interval=k, policy=pol)
            step = FusedTrainStep(lambda i, l: model(i, labels=l), opt)
            ids, labels = _batch(cfg, seed=2)
            return [float(step(ids, labels)) for _ in range(3)]

        assert run(interval, policy) == run(1, "full")

    def test_interval_must_divide_layers(self):
        cfg, model, opt = _build(seed=4, interval=5)  # gpt_tiny: 2 layers
        model.train()
        step = FusedTrainStep(lambda i, l: model(i, labels=l), opt)
        ids, labels = _batch(cfg)
        with pytest.raises(ValueError, match="must divide"):
            step(ids, labels)


class TestDataLoaderPrefetchWindow:
    def test_window_clamped_at_zero_workers(self):
        """Regression: num_workers * prefetch_factor == 0 collapsed the
        single-process pipeline to depth 0 — clamp to >= 1."""
        class DS(Dataset):
            def __getitem__(self, i):
                return np.int64(i)

            def __len__(self):
                return 8

        dl = DataLoader(DS(), batch_size=2, num_workers=0)
        assert dl.prefetch_window >= 1
        # prefetch_factor keeps its meaning in single-process mode: the
        # buffered reader's queue must stay prefetch_factor deep, not 1
        dl4 = DataLoader(DS(), batch_size=2, num_workers=0,
                         prefetch_factor=4)
        assert dl4.prefetch_window == 4
        dl2 = DataLoader(DS(), batch_size=2, num_workers=3,
                         prefetch_factor=4)
        assert dl2.prefetch_window == 12
        # the clamped window still iterates correctly
        out = [np.asarray(b._value).tolist() for b in dl]
        assert out == [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_dataloader_device_prefetch(self):
        class DS(Dataset):
            def __getitem__(self, i):
                return np.full((3,), i, np.float32)

            def __len__(self):
                return 6

        dl = DataLoader(DS(), batch_size=2, num_workers=0)
        pf = dl.device_prefetch(depth=2)
        got = [np.asarray(b._value)[:, 0].tolist() for b in pf]
        assert got == [[0.0, 1.0], [2.0, 3.0], [4.0, 5.0]]
        assert pf.stats()["batches"] == 3


class TestDevicePrefetcher:
    def test_order_and_stats(self):
        def gen():
            for i in range(5):
                yield {"x": np.full((2,), i, np.float32),
                       "pair": (np.int64(i), [np.float32(i)])}

        pf = DevicePrefetcher(gen(), depth=2)
        seen = []
        for b in pf:
            assert isinstance(b, dict)
            seen.append(int(np.asarray(b["x"]._value)[0]))
        assert seen == [0, 1, 2, 3, 4]
        st = pf.stats()
        assert st["batches"] == 5
        assert st["stall_seconds_total"] >= 0.0

    def test_stall_histogram_records_per_batch(self):
        from paddle_tpu.telemetry import registry

        hist = registry().histogram("train_input_stall_seconds")
        before = hist.summary().get("count", 0)
        pf = DevicePrefetcher((np.zeros((2,), np.float32)
                               for _ in range(4)), depth=1)
        assert sum(1 for _ in pf) == 4
        assert hist.summary().get("count", 0) - before == 4

    def test_source_error_propagates(self):
        def gen():
            yield np.zeros((2,), np.float32)
            raise RuntimeError("boom in source")

        pf = DevicePrefetcher(gen(), depth=2)
        next(pf)
        with pytest.raises(RuntimeError, match="boom in source"):
            for _ in pf:
                pass

    def test_early_close_releases_producer(self):
        def gen():
            for i in range(100):
                yield np.full((2,), i, np.float32)

        pf = DevicePrefetcher(gen(), depth=2)
        next(pf)
        pf.close()
        assert not pf._thread.is_alive()
        with pytest.raises(StopIteration):
            next(pf)

    def test_wrap_tensors_false_yields_raw_arrays(self):
        import jax

        pf = DevicePrefetcher((np.ones((2,), np.float32) for _ in range(2)),
                              depth=1, wrap_tensors=False)
        b = next(pf)
        assert isinstance(b, jax.Array)
        pf.close()

    def test_invalid_depth(self):
        with pytest.raises(ValueError):
            DevicePrefetcher(iter(()), depth=0)


class TestTrainRematAutotune:
    SHAPE = {"layers": 12, "hidden": 768, "batch": 16, "seq": 1024}

    def test_enumeration_and_defaults(self):
        from paddle_tpu.analysis import autotune

        cands = autotune.enumerate_candidates("train_remat", self.SHAPE,
                                              "bfloat16")
        assert {"interval": 0, "policy": 0} in cands  # remat off
        assert {"interval": 1, "policy": 1} in cands  # historical default
        for c in cands:
            k = c["interval"]
            assert k == 0 or self.SHAPE["layers"] % k == 0
        assert autotune.default_params("train_remat", self.SHAPE,
                                       "bfloat16") == {"interval": 1,
                                                       "policy": 1}

    def test_param_config_mapping_roundtrip(self):
        from paddle_tpu.analysis import autotune

        assert autotune.remat_params_to_config(
            {"interval": 0, "policy": 0}) == (0, None)
        assert autotune.remat_params_to_config(
            {"interval": 2, "policy": 2}) == (2, "dots")
        for iv, pol in [(0, None), (1, "full"), (4, "dots")]:
            params = autotune.remat_config_to_params(iv, pol)
            assert autotune.remat_params_to_config(params) == (
                (iv, pol) if iv > 0 else (0, None))

    def test_table_roundtrip_and_dispatch(self, tmp_path, monkeypatch):
        from paddle_tpu.analysis import autotune

        path = str(tmp_path / "table.json")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_TABLE", path)
        autotune.reset()
        try:
            t = autotune.AutotuneTable()
            t.put("train_remat", self.SHAPE, "bfloat16",
                  {"interval": 2, "policy": 2}, measured_us=123.0,
                  source="measured", device="test")
            assert autotune.validate_table(t) == []
            t.save(path)
            autotune.reset()
            got = autotune.kernel_params("train_remat", self.SHAPE,
                                         "bfloat16")
            assert got == {"interval": 2, "policy": 2}
            # an illegal entry fails strict replay
            t.put("train_remat", self.SHAPE, "bfloat16",
                  {"interval": 5, "policy": 1})
            t.save(path)
            with pytest.raises(ValueError):
                autotune.load_table(path, strict=True)
        finally:
            autotune.reset()

    def test_committed_table_covers_bench_train_shapes(self):
        """The packaged table seeds train_remat entries for the bench
        ladder's pure-bf16 rungs, so bench dispatch flows through the
        table before any chip measured anything."""
        from paddle_tpu.analysis import autotune

        table = autotune.load_table(os.path.join(
            os.path.dirname(autotune.__file__), "autotune_table.json"))
        for shape in ({"layers": 24, "hidden": 2048, "batch": 8,
                       "seq": 1024},
                      {"layers": 12, "hidden": 768, "batch": 16,
                       "seq": 1024}):
            assert table.get("train_remat", shape, "bfloat16") is not None


class TestFusedStepLint:
    def test_fused_master_step_gl004_clean(self):
        """The donation regression this PR is designed to prevent: with
        FLAGS_graph_lint, the fused master-weight step must carry ZERO
        GL004 findings (params, moments AND masters donated)."""
        from paddle_tpu import analysis

        pt.set_flags({"FLAGS_graph_lint": True})
        analysis.set_announce(False)
        try:
            cfg, model, opt = _build(seed=1, regime="master")
            step = FusedTrainStep(lambda i, l: model(i, labels=l), opt,
                                  amp_level="O1")
            ids, labels = _batch(cfg)
            float(step(ids, labels))
            reports = step.lint_reports()
            assert reports, "lint hook did not run"
            gl004 = [f for rep in reports for f in rep.findings
                     if f.code == "GL004"]
            assert not gl004, [f.render() for f in gl004]
        finally:
            pt.set_flags({"FLAGS_graph_lint": False})
