"""Activation functionals (reference: python/paddle/nn/functional/activation.py).
All are single fused VPU expressions under XLA."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops import dispatch
from ...ops._factory import ensure_tensor, unary_op

relu = unary_op(jax.nn.relu, "relu")
relu6 = unary_op(lambda x: jnp.clip(x, 0, 6), "relu6")
sigmoid = unary_op(jax.nn.sigmoid, "sigmoid")
tanh = unary_op(jnp.tanh, "tanh")
silu = unary_op(jax.nn.silu, "silu")
swish = silu
mish = unary_op(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
log_sigmoid = unary_op(jax.nn.log_sigmoid, "log_sigmoid")
softsign = unary_op(jax.nn.soft_sign, "softsign")
tanhshrink = unary_op(lambda x: x - jnp.tanh(x), "tanhshrink")


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jax.nn.gelu(a, approximate=approximate), x, op_name="gelu"
    )


def leaky_relu(x, negative_slope=0.01, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jax.nn.leaky_relu(a, negative_slope), x, op_name="leaky_relu"
    )


def elu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, op_name="selu"
    )


def celu(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.clip(a, min, max), x, op_name="hardtanh")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x, op_name="hardsigmoid"
    )


def hardswish(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, op_name="hardswish"
    )


def hardshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x, op_name="hardshrink"
    )


def softshrink(x, threshold=0.5, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ),
        x,
        op_name="softshrink",
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.where(
            a * beta > threshold, a, jax.nn.softplus(a * beta) / beta
        ),
        x,
        op_name="softplus",
    )


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def fn(a, w):
        if w.size > 1:
            ax = 1 if data_format == "NCHW" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ax] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, a * w)

    return dispatch.apply(fn, x, weight, op_name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    x = ensure_tensor(x)
    if training:
        from ...ops.random import default_generator

        key = default_generator.split()

        def fn(a):
            slopes = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, a * slopes)

        return dispatch.apply(fn, x, op_name="rrelu")
    mid = (lower + upper) / 2.0
    return dispatch.apply(lambda a: jnp.where(a >= 0, a, a * mid), x, op_name="rrelu")


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return dispatch.apply(lambda a: jax.nn.softmax(a, axis=axis), x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return dispatch.apply(
        lambda a: jax.nn.log_softmax(a, axis=axis), x, op_name="log_softmax"
    )


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = ensure_tensor(x)
    from ...ops.random import default_generator

    key = default_generator.split()

    def fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y

    return dispatch.apply(fn, x, op_name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jax.nn.glu(a, axis=axis), x, op_name="glu")


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = list(a.shape[:ax]) + [c // groups, groups] + list(a.shape[ax + 1 :])
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return dispatch.apply(fn, x, op_name="maxout")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.where(a > threshold, a, value), x, op_name="thresholded_relu"
    )
