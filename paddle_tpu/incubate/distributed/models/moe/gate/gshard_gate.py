"""GShard top-2 gate (reference gate/gshard_gate.py): top-2 routing; the
aux load-balance loss is computed by MoELayer from the pre-capacity
assignment. `capacity` feeds MoELayer's capacity factor. random_routing
(stochastic second-expert drop) is accepted for API parity but not yet
implemented — routing is deterministic top-2."""
from __future__ import annotations

from .naive_gate import NaiveGate


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity = capacity
        self.random_routing = random_routing
