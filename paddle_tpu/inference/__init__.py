"""Inference serving surface.

Reference: paddle/fluid/inference/api/analysis_predictor.h:94
(AnalysisPredictor: load program -> IR pass pipeline -> NaiveExecutor,
zero-copy input/output tensors) and python/paddle/inference/wrapper.py
(Config / Predictor / create_predictor).

TPU-native redesign: the "inference program" is a serialized StableHLO
executable (jit.save / jax.export).  The Predictor loads it, binds named
input handles, and runs the compiled program — XLA took the place of the
Analyzer's 200+ IR passes, and "zero copy" is the natural mode (device
arrays are handed to the executable without staging).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..tensor import Tensor as _FrameworkTensor

__all__ = [
    "Config", "Predictor", "Tensor", "create_predictor",
    "DataType", "PlaceType", "PrecisionType", "get_version",
    "get_num_bytes_of_data_type", "PredictorPool",
]


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT32 = "int32"
    INT64 = "int64"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


class PlaceType:
    CPU = "cpu"
    GPU = "tpu"  # the accelerator in this build is the TPU
    TPU = "tpu"


class PrecisionType:
    Float32 = "fp32"
    Bfloat16 = "bf16"
    Half = "fp16"
    Int8 = "int8"


class Config:
    """reference wrapper.py Config / analysis_config.h: model path +
    runtime knobs.  XLA owns the optimization pipeline, so pass toggles
    are accepted for API parity and recorded into ``summary()``."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # jit.save writes a single <path> prefix; accept either the prefix
        # or the reference's (prog, params) pair pointing at it
        self._model_prefix = prog_file
        self._use_tpu = True
        self._device_id = 0
        self._enable_memory_optim = True
        self._switches: Dict[str, object] = {}
        self._causal_lm_model = None
        self._decode_opts: Optional[Dict[str, object]] = None

    def set_model(self, prog_file, params_file=None):
        self._model_prefix = prog_file

    # -- causal-LM decode mode --------------------------------------------
    def set_causal_lm_model(self, model):
        """Serve a LIVE causal-LM (a model exposing ``generate()``) instead
        of a saved static-shape program.  A saved StableHLO artifact cannot
        run the autoregressive loop (its programs are single static calls);
        the live model's decode engine compiles exactly two programs
        (prefill + decode) and reuses them across every ``run()``."""
        self._causal_lm_model = model
        return self

    def enable_causal_lm_decode(self, max_new_tokens: int = 32,
                                do_sample: bool = False,
                                temperature: float = 1.0, top_k: int = 0,
                                top_p: Optional[float] = None,
                                eos_token_id: Optional[int] = None,
                                max_seq_len: Optional[int] = None,
                                cache_dtype: str = "bfloat16"):
        """Switch ``Predictor.run`` to autoregressive decode: input handle
        x0 takes int64 prompt ids [B, S0]; output handle out0 returns
        [B, S0 + max_new_tokens] generated ids."""
        self._decode_opts = dict(
            max_new_tokens=int(max_new_tokens), do_sample=bool(do_sample),
            temperature=float(temperature), top_k=int(top_k), top_p=top_p,
            eos_token_id=eos_token_id, max_seq_len=max_seq_len,
            cache_dtype=str(cache_dtype))
        return self

    def causal_lm_decode_enabled(self) -> bool:
        return self._decode_opts is not None

    def model_dir(self):
        return self._model_prefix

    def prog_file(self):
        return self._model_prefix

    # device selection (reference enable_use_gpu / disable_gpu)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_tpu = False

    def use_gpu(self):
        return self._use_tpu

    def _note_inert(self, knob, value):
        """One-time (per knob) notice: the switch is recorded for API
        parity but has no effect on XLA — nothing is silently ignored
        without a trace (round-3 weak #9)."""
        if knob not in self._switches:
            import sys

            sys.stderr.write(
                f"[paddle_tpu.inference] Config.{knob}={value!r} accepted; "
                "inert on XLA/TPU (the compiler owns this decision)\n")
        self._switches[knob] = value

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag
        self._note_inert("memory_optim", flag)

    def switch_ir_optim(self, flag=True):
        self._note_inert("ir_optim", flag)  # XLA always optimizes

    def switch_use_feed_fetch_ops(self, flag=False):
        self._note_inert("feed_fetch", flag)

    def set_cpu_math_library_num_threads(self, n):
        self._note_inert("cpu_threads", n)

    def summary(self):
        lines = [f"model: {self._model_prefix}",
                 f"device: {'tpu' if self._use_tpu else 'cpu'}:{self._device_id}",
                 "compiler: XLA (StableHLO program from jit.save)"]
        if self._decode_opts is not None:
            lines.append(f"causal_lm_decode: {self._decode_opts}")
        lines += [f"{k}: {v}" for k, v in self._switches.items()]
        return "\n".join(lines)


class Tensor:
    """Named IO handle (reference wrapper.py Tensor / zero-copy tensor):
    copy_from_cpu binds, copy_to_cpu fetches."""

    def __init__(self, name: str, owner: "Predictor"):
        self._name = name
        self._owner = owner

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._owner._inputs[self._name] = np.asarray(data)

    def share_external_data(self, tensor):
        v = tensor._value if isinstance(tensor, _FrameworkTensor) else tensor
        self._owner._inputs[self._name] = v  # zero-copy: device array as-is

    def copy_to_cpu(self):
        return np.asarray(self._owner._outputs[self._name])

    def shape(self):
        v = (self._owner._outputs.get(self._name)
             if self._name in self._owner._outputs
             else self._owner._inputs.get(self._name))
        return list(np.asarray(v).shape) if v is not None else None


class Predictor:
    """reference analysis_predictor.h:94 — but execution is one compiled
    XLA call (ZeroCopyRun -> jitted program)."""

    def __init__(self, config: Config):
        self._config = config
        self._causal_lm = config._causal_lm_model
        if config.causal_lm_decode_enabled() and self._causal_lm is None:
            raise RuntimeError(
                "enable_causal_lm_decode() needs a live model: saved "
                "StableHLO programs are single static-shape calls and "
                "cannot run the autoregressive loop; attach the model with "
                "Config.set_causal_lm_model(model)")
        if self._causal_lm is not None and not config.causal_lm_decode_enabled():
            raise RuntimeError(
                "set_causal_lm_model() without enable_causal_lm_decode(): "
                "decode options must be chosen explicitly (max_new_tokens, "
                "sampling, cache dtype) — call "
                "Config.enable_causal_lm_decode(...) before create_predictor")
        if self._causal_lm is not None:
            if not hasattr(self._causal_lm, "generate"):
                raise RuntimeError(
                    "set_causal_lm_model expects a model with generate() "
                    "(GenerationMixin)")
            self._layer = None
            self._n_inputs = 1
        else:
            from ..jit.save_load import load as _load

            self._layer = _load(config.prog_file())
            self._n_inputs = getattr(self._layer, "n_inputs", None)
            if self._n_inputs is None:
                raise RuntimeError(
                    "cannot determine the model's input arity from "
                    f"'{config.prog_file()}': the artifact predates jit.save's "
                    "n_inputs field and the exported program did not expose its "
                    "calling convention; re-save the model with jit.save")
        self._input_names = [f"x{i}" for i in range(self._n_inputs)]
        self._inputs: Dict[str, object] = {}
        self._outputs: Dict[str, object] = {}
        self._output_names: List[str] = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self)

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self)

    def run(self, inputs: Optional[list] = None):
        import contextlib

        import jax

        from ..tensor import to_tensor

        if inputs is not None:
            for i, a in enumerate(inputs):
                self._inputs[f"x{i}"] = np.asarray(
                    a._value if isinstance(a, _FrameworkTensor) else a)
        args = [to_tensor(self._inputs[k])
                for k in sorted(self._inputs, key=lambda s: int(s[1:]))]
        # device selection is REAL: Config.disable_gpu() pins execution to
        # the host CPU backend (reference enable_use_gpu/disable_gpu)
        if not self._config.use_gpu():
            try:
                ctx = jax.default_device(jax.devices("cpu")[0])
            except RuntimeError:
                ctx = contextlib.nullcontext()
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            if self._causal_lm is not None:
                opts = self._config._decode_opts or {}
                out = self._causal_lm.generate(args[0], **opts)
            else:
                out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: o._value for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [_FrameworkTensor(v) for v in self._outputs.values()]
        return True

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version():
    from ..version import __version__

    return __version__


def get_num_bytes_of_data_type(dtype) -> int:
    return int(np.dtype(str(dtype)).itemsize)


class PredictorPool:
    """reference api PredictorPool: N predictors sharing one program."""

    def __init__(self, config: Config, size: int = 1):
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrive(self, idx: int) -> Predictor:  # (sic) reference spelling
        return self._predictors[idx]

    retrieve = retrive
