"""Shared fault-injection harness (serving engine + distributed layer).

Promoted from ``serving/faults.py`` (which keeps compatible re-exports)
so the distributed fault-tolerance layer can drive the SAME
occurrence-keyed injector: components call a test-only
``_fault_hook(point, ctx)`` at named points of their pipeline; an
installed :class:`FaultInjector` acts there — raising, stalling, or
mutating ``ctx`` — to force, deterministically and at chosen
occurrences, exactly the failures production would hit stochastically.

Serving points (see serving/engine.py):

======================  =====================  ==============================
kind                    hook point             effect
======================  =====================  ==============================
``step_exception``      before_decode          raise :class:`InjectedFault`
                                               (``state_intact=True`` — the
                                               fault fires before dispatch)
``step_stall``          before_decode          ``time.sleep(duration)`` so
                                               the watchdog trips; the thunk
                                               then honors ``cancelled()``
``nan_logits``          after_decode           flip ``ctx["finite"]`` for
                                               the chosen slots (simulating
                                               NaN-poisoned logits)
``alloc_exhausted``     alloc                  ``ctx["force_none"] = True``
                                               (pool reports no free pages)
``callback_error``      callback               raise inside the engine's
                                               ``on_token`` invocation
======================  =====================  ==============================

Disaggregated hand-off points (serving/disagg.py — PR 20):

======================  =====================  ==============================
``transfer_stall``      page_transfer          ``time.sleep(duration)`` in
                                               the middle of a page hand-off
``transfer_error``      page_transfer          raise :class:`InjectedFault`
                                               mid-transfer — the destination
                                               reservation must roll back and
                                               the source retain ownership
``transfer_partial``    page_transfer          ``ctx["partial"] = True`` —
                                               only part of the page set
                                               lands; the transfer layer
                                               treats it as failed (rollback
                                               + source keeps the request)
======================  =====================  ==============================

Distributed points (docs/distributed_faults.md):

======================  =====================  ==============================
``store_error``         store_op               raise inside a TCPStore op —
                                               absorbed by the bounded retry
                                               when transient, escalating to
                                               ``StoreUnavailableError`` when
                                               persistent
``beat_skip``           heartbeat              ``ctx["skip"] = True`` — the
                                               ElasticManager misses beats so
                                               peers see this rank as dead
``exchange_stall``      exchange               ``time.sleep(duration)`` before
                                               a store-backed collective
                                               posts its payload
``exchange_error``      exchange               raise inside the collective
======================  =====================  ==============================

Injection points are keyed on the Nth OCCURRENCE of the point (per-point
call counters), so a schedule is reproducible independent of wall clock.
``FaultInjector.log`` records every shot actually fired — tests assert the
schedule really executed instead of silently passing on a dead plan.

``random_schedule`` builds a randomized multi-fault serving plan and
``random_store_schedule`` a randomized store-outage storm, both from a
seeded RNG, for the property tests and the CI gates
(tools/serving_fault_gate.py, tools/dist_fault_gate.py).
"""
from __future__ import annotations

import time
from collections import Counter
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["InjectedFault", "FaultPlan", "FaultInjector", "random_schedule",
           "random_store_schedule", "random_transfer_schedule",
           "KINDS", "KIND_POINTS"]

KIND_POINTS = {
    # serving (engine/allocator hook points)
    "step_exception": ("before_decode",),
    "step_stall": ("before_decode",),
    "nan_logits": ("after_decode",),
    "alloc_exhausted": ("alloc",),
    "callback_error": ("callback",),
    # distributed (store / elastic / collective hook points)
    "store_error": ("store_op",),
    "beat_skip": ("heartbeat",),
    "exchange_stall": ("exchange",),
    "exchange_error": ("exchange",),
    # cluster (sharded serving / traffic-driver hook points — PR 19):
    # ``replica_kill`` fires at ShardedServingEngine's per-tick
    # ``cluster_step`` point and appends ``plan.slots`` (replica indices,
    # default [0]) to ``ctx["kill"]``; the cluster closes those replicas
    # and re-homes their live requests.  ``load_spike`` fires at a
    # traffic driver's ``traffic`` point and multiplies
    # ``ctx["multiplier"]`` by ``plan.duration`` (the spike factor) —
    # the driver submits that many times its baseline arrivals.
    "replica_kill": ("cluster_step",),
    "load_spike": ("traffic",),
    # disaggregated hand-off (serving/disagg.py — PR 20): all three fire
    # at the PageTransfer's ``page_transfer`` point, between the
    # destination-side reservation and the commit, so every schedule
    # exercises the mid-transfer ownership protocol.  Plans naming any
    # other point are rejected by FaultPlan validation (the PR 8
    # retired-point discipline).
    "transfer_stall": ("page_transfer",),
    "transfer_error": ("page_transfer",),
    "transfer_partial": ("page_transfer",),
}

KINDS = tuple(KIND_POINTS)


class InjectedFault(RuntimeError):
    """A deterministically injected fault.

    ``state_intact=True`` (the default) tells the serving engine the
    fault fired BEFORE any device dispatch — pool state is untouched, so
    containment can stay surgical (fail one request / retry without a
    rebuild).  Schedules that model a mid-dispatch crash set it False to
    force the conservative rebuild path.  (The distributed layer treats
    any InjectedFault from a store op as a transport failure.)"""

    def __init__(self, msg: str, state_intact: bool = True):
        super().__init__(msg)
        self.state_intact = state_intact


@dataclass
class FaultPlan:
    """One injection: fire ``kind`` at occurrences [at, at+times) of
    ``point``."""

    point: str                     # hook point name
    at: int                        # 0-based occurrence index of the point
    kind: str                      # one of KINDS
    times: int = 1                 # consecutive occurrences to fire on
    duration: float = 0.0          # step_stall/exchange_stall: sleep
    #                                seconds; load_spike: spike multiplier
    slots: Optional[Sequence[int]] = None   # nan_logits: slot indices (None
    #                                         = every active slot);
    #                                         replica_kill: replica indices
    #                                         (None = replica 0)
    state_intact: bool = True      # step_exception: pre-dispatch fault?

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {KINDS}")
        if self.point not in KIND_POINTS[self.kind]:
            raise ValueError(
                f"kind {self.kind!r} cannot fire at point {self.point!r} "
                f"(valid: {KIND_POINTS[self.kind]})")
        if self.times < 1:
            raise ValueError("times must be >= 1")


@dataclass
class _Shot:
    """One fault that actually fired (FaultInjector.log entry)."""

    point: str
    occurrence: int
    kind: str


class FaultInjector:
    """Deterministic fault scheduler implementing the shared
    ``_fault_hook(point, ctx)`` protocol.

    Usage::

        inj = FaultInjector()
        inj.inject("before_decode", at=3, kind="step_exception")  # transient
        inj.inject("store_op", at=10, kind="store_error", times=2)
        inj.install(engine_or_store_or_manager)
        ... drive it; assert inj.log shows the shots fired ...
    """

    def __init__(self, plans: Optional[List[FaultPlan]] = None):
        self.plans: List[FaultPlan] = list(plans or [])
        self.log: List[_Shot] = []
        self._calls: Counter = Counter()

    def inject(self, point: str, at: int, kind: str, **kw) -> "FaultInjector":
        self.plans.append(FaultPlan(point=point, at=at, kind=kind, **kw))
        return self

    def install(self, target) -> "FaultInjector":
        """Attach to any component exposing ``_fault_hook`` (ServingEngine
        + its allocator, TCPStore, ElasticManager, ...)."""
        target._fault_hook = self.hook
        allocator = getattr(target, "allocator", None)
        if allocator is not None:
            allocator._fault_hook = self.hook
        return self

    # -- the hook ----------------------------------------------------------
    def hook(self, point: str, ctx: Optional[dict] = None):
        n = self._calls[point]
        self._calls[point] += 1
        for plan in self.plans:
            if plan.point != point or not plan.at <= n < plan.at + plan.times:
                continue
            self.log.append(_Shot(point, n, plan.kind))
            self._fire(plan, n, ctx)

    def _fire(self, plan: FaultPlan, n: int, ctx: Optional[dict]):
        if plan.kind == "step_exception":
            raise InjectedFault(
                f"injected step exception at {plan.point}#{n}",
                state_intact=plan.state_intact)
        if plan.kind in ("step_stall", "exchange_stall", "transfer_stall"):
            time.sleep(plan.duration)
            return
        if plan.kind == "nan_logits":
            fin = ctx["finite"] if ctx else None
            if fin is not None:
                if plan.slots is None:
                    fin[:] = False
                else:
                    for s in plan.slots:
                        if s < len(fin):
                            fin[s] = False
            return
        if plan.kind == "alloc_exhausted":
            if ctx is not None:
                ctx["force_none"] = True
            return
        if plan.kind == "callback_error":
            raise InjectedFault(
                f"injected callback error at {plan.point}#{n}")
        if plan.kind == "store_error":
            op = (ctx or {}).get("op", "?")
            raise InjectedFault(
                f"injected store fault at {plan.point}#{n} (op={op})")
        if plan.kind == "beat_skip":
            if ctx is not None:
                ctx["skip"] = True
            return
        if plan.kind == "exchange_error":
            raise InjectedFault(
                f"injected collective fault at {plan.point}#{n}")
        if plan.kind == "replica_kill":
            if ctx is not None:
                ctx.setdefault("kill", []).extend(
                    plan.slots if plan.slots is not None else [0])
            return
        if plan.kind == "load_spike":
            if ctx is not None:
                ctx["multiplier"] = (ctx.get("multiplier", 1.0)
                                     * max(plan.duration, 1.0))
            return
        if plan.kind == "transfer_error":
            raise InjectedFault(
                f"injected transfer fault at {plan.point}#{n}")
        if plan.kind == "transfer_partial":
            if ctx is not None:
                ctx["partial"] = True
            return

    # -- introspection -----------------------------------------------------
    def fired(self, kind: Optional[str] = None) -> int:
        """How many shots fired (optionally of one kind)."""
        return sum(1 for s in self.log if kind is None or s.kind == kind)

    def occurrences(self, point: str) -> int:
        """How many times the component reached ``point``."""
        return self._calls[point]


def random_schedule(rng: np.random.RandomState, *, horizon: int = 40,
                    n_faults: int = 4, num_slots: int = 4,
                    include_stalls: bool = False,
                    stall_duration: float = 0.3) -> FaultInjector:
    """Build a randomized serving fault schedule over roughly ``horizon``
    decode steps: the property tests and the CI gate drive engines under
    many seeds and assert the accounting/containment invariants hold for
    ALL of them.  Stalls are opt-in (they cost wall clock per shot and
    need a watchdog-enabled engine)."""
    kinds = ["step_exception", "nan_logits", "alloc_exhausted",
             "callback_error"]
    if include_stalls:
        kinds.append("step_stall")
    inj = FaultInjector()
    for _ in range(n_faults):
        kind = kinds[rng.randint(len(kinds))]
        at = int(rng.randint(1, horizon))
        if kind == "step_exception":
            # times=1 exercises retry-once; times>=2 forces recovery
            inj.inject("before_decode", at=at, kind=kind,
                       times=int(rng.randint(1, 4)))
        elif kind == "step_stall":
            inj.inject("before_decode", at=at, kind=kind,
                       duration=stall_duration)
        elif kind == "nan_logits":
            inj.inject("after_decode", at=at, kind=kind,
                       slots=[int(rng.randint(num_slots))])
        elif kind == "alloc_exhausted":
            inj.inject("alloc", at=at, kind=kind,
                       times=int(rng.randint(1, 6)))
        else:
            inj.inject("callback", at=at, kind=kind)
    return inj


def random_transfer_schedule(rng: np.random.RandomState, *,
                             horizon: int = 12, n_faults: int = 3,
                             include_stalls: bool = False,
                             stall_duration: float = 0.05) -> FaultInjector:
    """Randomized mid-transfer fault schedule for the disaggregated
    hand-off (serving/disagg.py): ``transfer_error`` / ``transfer_partial``
    shots at random occurrences of the ``page_transfer`` point.  The
    property tests assert that under ANY seed both pools' 4-term page
    accounting stays exact and every request still reaches a typed
    terminal state — transfers may fail, ownership may not leak."""
    kinds = ["transfer_error", "transfer_partial"]
    if include_stalls:
        kinds.append("transfer_stall")
    inj = FaultInjector()
    for _ in range(n_faults):
        kind = kinds[rng.randint(len(kinds))]
        at = int(rng.randint(0, max(horizon, 1)))
        if kind == "transfer_stall":
            inj.inject("page_transfer", at=at, kind=kind,
                       duration=stall_duration)
        else:
            inj.inject("page_transfer", at=at, kind=kind,
                       times=int(rng.randint(1, 3)))
    return inj


def random_store_schedule(rng: np.random.RandomState, *, horizon: int = 200,
                          n_faults: int = 5,
                          max_burst: int = 3) -> FaultInjector:
    """Randomized store-outage storm: bursts of transient ``store_error``
    at random occurrences of the ``store_op`` point.  Bursts are kept
    non-overlapping and no longer than the default retry budget
    (PADDLE_STORE_RETRIES=3 → 4 attempts), so under ANY seed the storm
    must be fully absorbed by retry — the invariant the dist fault gate
    asserts."""
    ats = sorted(int(rng.randint(1, horizon)) for _ in range(n_faults))
    inj = FaultInjector()
    prev_end = -1
    for at in ats:
        if at <= prev_end + 1:  # keep bursts from fusing past the budget
            continue
        times = int(rng.randint(1, max_burst + 1))
        inj.inject("store_op", at=at, kind="store_error", times=times)
        prev_end = at + times
    return inj
