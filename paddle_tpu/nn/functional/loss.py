"""Loss functionals (reference: python/paddle/nn/functional/loss.py;
softmax_with_cross_entropy kernel phi/kernels/gpu/cross_entropy_kernel.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor import Tensor
from ...ops import dispatch
from ...ops._factory import ensure_tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))

    def fn(logits, lab, *w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30)
        )
        n_classes = logits.shape[axis]
        if soft_label:
            tgt = lab
            if label_smoothing > 0:
                tgt = tgt * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(tgt * lp, axis=axis)
        else:
            lab_idx = lab
            if lab_idx.ndim == lp.ndim:
                lab_idx = jnp.squeeze(lab_idx, axis=axis)
            lab_idx = lab_idx.astype(jnp.int32)
            valid = lab_idx != ignore_index
            safe = jnp.where(valid, lab_idx, 0)
            picked = jnp.take_along_axis(
                lp, jnp.expand_dims(safe, axis), axis=axis
            ).squeeze(axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(lp, axis=axis)
                loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
            else:
                loss = -picked
            loss = jnp.where(valid, loss, 0.0)
            if has_w:
                wv = jnp.take(w[0], safe)
                wv = jnp.where(valid, wv, 0.0)
                loss = loss * wv
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wv), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return dispatch.apply(fn, *tensors, op_name="cross_entropy")


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True,
    return_softmax=False, axis=-1,
):
    out = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    out = out.unsqueeze(axis) if not soft_label else out
    if return_softmax:
        from .activation import softmax

        return out, softmax(logits, axis=axis)
    return out


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)
    return dispatch.apply(
        lambda a, b: _reduce(jnp.square(a - b), reduction), input, label, op_name="mse_loss"
    )


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)
    return dispatch.apply(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, op_name="l1_loss"
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(loss * delta, reduction)

    return dispatch.apply(fn, input, label, op_name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """reference phi huber_loss: quadratic below delta, linear above
    (NOT delta-rescaled like smooth_l1)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input, label, op_name="huber_loss")


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    """reference phi log_loss: -y*log(p+eps) - (1-y)*log(1-p+eps),
    elementwise (no reduction)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(p, y):
        return (-y * jnp.log(p + epsilon)
                - (1.0 - y) * jnp.log(1.0 - p + epsilon))

    return dispatch.apply(fn, input, label, op_name="log_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """reference phi margin_cross_entropy (ArcFace/CosFace margins):
    the target-class cosine logit is replaced by
    cos(margin1*theta + margin2) - margin3, everything scaled by
    ``scale`` before softmax cross-entropy.  Single-group path (the
    reference's model-parallel class split rides the mp sharding of the
    logits instead)."""
    logits, label = ensure_tensor(logits), ensure_tensor(label)

    def fn(z, y):
        if y.ndim == z.ndim:  # [N, 1] labels (paddle convention)
            y = jnp.squeeze(y, axis=-1)
        onehot = jax.nn.one_hot(y, z.shape[-1], dtype=z.dtype)
        # clip strictly inside (-1, 1): d(arccos) blows up at the
        # boundary and a converged class hits exactly 1.0 in fp32
        eps = 1e-6
        cos_t = jnp.clip(jnp.sum(onehot * z, axis=-1),
                         -1.0 + eps, 1.0 - eps)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        mod = z + onehot * (target - cos_t)[:, None]
        mod = mod * scale
        logp = jax.nn.log_softmax(mod, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1)
        return _reduce(loss, reduction), jnp.exp(logp)

    loss, sm = dispatch.apply(fn, logits, label,
                              op_name="margin_cross_entropy")
    if return_softmax:
        return loss, sm
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))

    def fn(lp, lab, *w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(lp, safe[:, None], axis=1).squeeze(1)
        loss = -picked
        if has_w:
            wv = jnp.take(w[0], safe)
            loss = loss * wv
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wv, 0.0)), 1e-12)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)

    return dispatch.apply(fn, *tensors, op_name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))

    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return dispatch.apply(fn, *tensors, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    tensors = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_pw:
        tensors.append(ensure_tensor(pos_weight))

    def fn(z, y, *rest):
        i = 0
        w = None
        pw = None
        if has_w:
            w = rest[i]
            i += 1
        if has_pw:
            pw = rest[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight factor
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return dispatch.apply(fn, *tensors, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input, label, op_name="kl_div")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input, label, op_name="hinge_embedding_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    input, other, label = ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)

    def fn(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    input1, input2, label = ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)

    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input1, input2, label, op_name="cosine_embedding_loss")


def triplet_margin_loss(
    input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False,  # noqa: A002
    reduction="mean", name=None,
):
    input, positive, negative = (
        ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative),
    )

    def fn(a, pos, neg):
        def dst(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), axis=-1), 1.0 / p)

        d_pos = dst(a, pos)
        d_neg = dst(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dst(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)

    return dispatch.apply(fn, input, positive, negative, op_name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    Reference: paddle/phi/kernels/cpu/warpctc_kernel.cc (dynloaded warpctc
    C library) and python/paddle/nn/functional/loss.py ctc_loss.  TPU-native
    redesign: the alpha (forward) recursion of Graves et al. runs in log
    space as one ``lax.scan`` over time with the whole batch and the
    2L+1-wide extended label tape vectorized per step — static shapes, no
    host loop, and the backward pass is JAX autodiff through the scan
    (replacing warpctc's hand-written beta recursion).

    ``log_probs``: [T, B, C] UNNORMALIZED logits (the reference's warpctc
    applies softmax internally; so do we).  ``labels``: int [B, Lmax].
    """
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)
    NEG = -1e30

    def fn(lp, lab, ilen, llen):
        T, B, C = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        ilen = ilen.astype(jnp.int32)
        llen = llen.astype(jnp.int32)
        Lmax = lab.shape[1]
        S = 2 * Lmax + 1

        s = jnp.arange(S)
        lab_idx = jnp.clip((s - 1) // 2, 0, max(Lmax - 1, 0))
        # extended tape: blank, l1, blank, l2, ..., blank   [B, S]
        ext = jnp.where((s % 2 == 0)[None, :], blank,
                        jnp.take_along_axis(
                            lab, jnp.broadcast_to(lab_idx[None, :], (B, S)),
                            axis=1))
        ext_prev2 = jnp.concatenate(
            [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
        allow_skip = ((s >= 2)[None, :] & (ext != blank)
                      & (ext != ext_prev2))
        # positions beyond this sample's tape (s > 2*llen) stay dead
        valid_s = s[None, :] <= (2 * llen)[:, None]

        emit0 = jnp.take_along_axis(lp[0], ext, axis=1)  # [B, S]
        alpha0 = jnp.where((s[None, :] <= 1) & valid_s, emit0, NEG)

        def step(alpha, xs):
            lp_t, t = xs
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            a2 = jnp.concatenate(
                [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            a3 = jnp.concatenate(
                [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            a3 = jnp.where(allow_skip, a3, NEG)
            new = jnp.logaddexp(jnp.logaddexp(alpha, a2), a3) + emit
            new = jnp.where(valid_s, new, NEG)
            # frozen past each sample's input length (loss reads T_b-1)
            new = jnp.where((t < ilen[:, None]), new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(
            step, alpha0, (lp[1:], jnp.arange(1, T)))
        end = jnp.clip(2 * llen, 0, S - 1)[:, None]          # final blank
        pre = jnp.clip(2 * llen - 1, 0, S - 1)[:, None]      # final label
        a_end = jnp.take_along_axis(alpha, end, axis=1)[:, 0]
        a_pre = jnp.where(
            llen > 0, jnp.take_along_axis(alpha, pre, axis=1)[:, 0], NEG)
        total = jnp.logaddexp(a_end, a_pre)
        # infeasible samples (input shorter than the label tape needs)
        # report inf like warpctc/torch, not the finite NEG sentinel —
        # isinf-based bad-sample filters must keep working
        loss = jnp.where(total <= NEG / 2, jnp.inf, -total)  # [B]
        if norm_by_times:
            # reference semantics: gradients (not the loss value) are
            # normalized by the number of time steps — value-preserving
            # grad rescale via the stop_gradient identity
            scaled = loss / jnp.maximum(ilen, 1).astype(loss.dtype)
            loss = scaled + jax.lax.stop_gradient(loss - scaled)
        if reduction == "mean":
            # reference mean: per-sample loss / label_length, then mean
            return jnp.mean(loss / jnp.maximum(llen, 1).astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return dispatch.apply(fn, log_probs, labels, input_lengths,
                          label_lengths, op_name="ctc_loss")


def square_error_cost(input, label):  # noqa: A002
    input, label = ensure_tensor(input), ensure_tensor(label)
    return dispatch.apply(lambda a, b: jnp.square(a - b), input, label, op_name="square_error_cost")


@jax.custom_vjp
def _lm_head_dot(h, w):
    """Chunk logits ``h [c, H] x w [V, H] -> fp32 [c, V]`` with a backward
    that casts the fp32 cotangent down to the operand dtype BEFORE the
    dW/dh contractions.  jax's derived vjp would contract fp32 d_logits
    against the bf16 operands directly — a silent mixed-dtype promotion
    that pushes both backward matmuls off the bf16 MXU path (graph_lint
    GL001; the owned flash kernel applies the same ``ds.astype(q.dtype)``
    discipline).  fp32 operands are untouched (the cast is a no-op)."""
    return jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _lm_head_dot_fwd(h, w):
    return _lm_head_dot(h, w), (h, w)


def _lm_head_dot_bwd(res, g):
    h, w = res
    gh = g.astype(h.dtype)
    gw = g.astype(w.dtype)
    # dh [c, H] = g [c, V] . w [V, H];  dw [V, H] = g^T [V, c] . h [c, H]
    dh = jax.lax.dot_general(gh, w, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dw = jax.lax.dot_general(gw, h, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return dh.astype(h.dtype), dw.astype(w.dtype)


_lm_head_dot.defvjp(_lm_head_dot_fwd, _lm_head_dot_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, *, chunk_tokens=2048,
                               compute_dtype=None, reduction="mean"):
    """LM-head matmul + softmax cross entropy without materializing the full
    [N, V] logits for backward.

    Reference analog: phi/kernels/gpu/cross_entropy_kernel.cu (fused
    softmax+CE) and operators/fused — but redesigned for the TPU memory
    hierarchy: tokens are processed in chunks under ``jax.checkpoint`` inside
    a ``lax.scan``, so at any moment only one chunk's logits live in HBM
    (fwd AND bwd — backward recomputes the chunk's logits, forms the
    softmax-minus-onehot product locally, and accumulates dW / dhidden).

    hidden: [..., H]; weight: [V, H] (tied LM head); labels: int[...].
    Returns scalar (mean/sum over tokens) or per-token loss [N].
    """
    hidden, weight, labels = (
        ensure_tensor(hidden), ensure_tensor(weight), ensure_tensor(labels),
    )

    def fn(h, w, lab):
        hs = h.shape[-1]
        h2 = h.reshape(-1, hs)
        lab1 = lab.reshape(-1).astype(jnp.int32)
        n = h2.shape[0]
        c = min(chunk_tokens, n)
        # pad to a whole number of chunks (padded tokens masked out)
        pad = (-n) % c
        if pad:
            h2 = jnp.concatenate([h2, jnp.zeros((pad, hs), h2.dtype)], 0)
            lab1 = jnp.concatenate([lab1, jnp.zeros((pad,), lab1.dtype)], 0)
        n_chunks = (n + pad) // c
        hc = h2.reshape(n_chunks, c, hs)
        lc = lab1.reshape(n_chunks, c)
        cdt = compute_dtype or h.dtype
        wt = w.astype(cdt)

        @jax.checkpoint
        def chunk_loss(hx, lx):
            # fp32 accumulation on the MXU out of low-precision operands
            logits = _lm_head_dot(hx.astype(cdt), wt)  # [c, V] fp32
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, lx[:, None], axis=-1)[:, 0]
            return lse - picked  # [c]

        def step(_, xs):
            hx, lx = xs
            return None, chunk_loss(hx, lx)

        _, losses = jax.lax.scan(step, None, (hc, lc))
        losses = losses.reshape(-1)[:n]
        if reduction == "mean":
            return jnp.mean(losses)
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    return dispatch.apply(fn, hidden, weight, labels,
                          op_name="fused_linear_cross_entropy")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference python/paddle/nn/functional/
    loss.py:872, phi hsigmoid_loss kernel over funcs/matrix_bit_code.h).

    Default complete-binary-tree coding (SimpleCode): for class l the
    code is c = l + num_classes; path node j has weight row
    (c >> (j+1)) - 1 and binary target bit j of c.  TPU-native: the whole
    [N, max_path] node/bit tables are computed with integer shifts, the
    node weights come from ONE gather, and the loss is a masked
    softplus(z) - bit*z sum — no per-sample host loop.  ``is_sparse`` is
    accepted for API parity (XLA gathers are already sparse-friendly).
    """
    input, label, weight = (ensure_tensor(input), ensure_tensor(label),
                            ensure_tensor(weight))
    bias_t = ensure_tensor(bias) if bias is not None else None
    pt_t = ensure_tensor(path_table) if path_table is not None else None
    pc_t = ensure_tensor(path_code) if path_code is not None else None

    def fn(x, lab, w, *rest):
        rest = list(rest)
        b = rest.pop(0) if bias_t is not None else None
        if pt_t is not None:
            ptab = rest.pop(0).astype(jnp.int32)
            pcode = rest.pop(0).astype(jnp.float32)
            valid = ptab >= 0
            idx = jnp.maximum(ptab, 0)
            bit = pcode
        else:
            c = lab.astype(jnp.int32) + num_classes        # [N]
            max_len = int(2 * num_classes - 1).bit_length() - 1
            j = jnp.arange(max_len)
            ks = jnp.arange(1, max_len + 2)
            length = jnp.sum((c[:, None] >> ks) > 0, axis=1)  # bitlen-1
            valid = j[None, :] < length[:, None]
            idx = jnp.maximum((c[:, None] >> (j[None, :] + 1)) - 1, 0)
            bit = ((c[:, None] >> j[None, :]) & 1).astype(jnp.float32)
        wn = w[idx]                                        # [N, L, D]
        z = jnp.einsum("nld,nd->nl", wn, x)
        if b is not None:
            z = z + b.reshape(-1)[idx]
        per_node = jax.nn.softplus(z) - bit * z
        loss = jnp.sum(jnp.where(valid, per_node, 0.0), axis=1)
        return loss[:, None]                               # [N, 1]

    args = [input, label, weight]
    if bias_t is not None:
        args.append(bias_t)
    if pt_t is not None:
        args.extend([pt_t, pc_t])
    return dispatch.apply(fn, *args, op_name="hsigmoid_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference python/paddle/nn/functional/
    loss.py:1912, dynloaded warp-transducer).

    TPU-native redesign: the alpha lattice recurrence
    a[t,u] = logaddexp(a[t-1,u] + blank(t-1,u), a[t,u-1] + emit(t,u-1))
    is evaluated by ONE ``lax.scan`` over ANTI-DIAGONALS d = t + u — both
    dependencies live on diagonal d-1, so every cell of a diagonal
    computes in parallel (vectorized over batch and u).  No per-cell
    host loop, static shapes, autodiff backward.  FastEmit regularization
    scales the emission-path gradient by (1 + lambda) via a
    value-preserving stop_gradient identity (warp-transducer's fastemit
    gradient scaling).

    input: [B, Tmax, Umax+1, V] logits (softmax applied internally, like
    the reference); label: int [B, Umax].
    """
    input, label = ensure_tensor(input), ensure_tensor(label)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)
    NEG = -1e30

    def fn(lp, lab, ilen, ulen):
        B, T, U1, V = lp.shape
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        ilen = ilen.astype(jnp.int32)
        ulen = ulen.astype(jnp.int32)
        blank_lp = lp[..., blank]                       # [B, T, U1]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U1 - 1, :],
            jnp.clip(lab, 0, V - 1)[:, None, :, None], axis=-1)[..., 0]
        if fastemit_lambda:
            emit_lp = ((1.0 + fastemit_lambda) * emit_lp
                       - fastemit_lambda * jax.lax.stop_gradient(emit_lp))

        u = jnp.arange(U1)
        alpha0 = jnp.where(u == 0, 0.0, NEG)[None, :].repeat(B, 0)
        # per-diagonal slices via explicit [B, U1] advanced indexing
        bidx = jnp.arange(B)[:, None]

        def step(alpha, d):
            t = d - u                                   # [U1]
            tb = jnp.clip(t - 1, 0, T - 1)
            from_blank = alpha + blank_lp[bidx, tb[None, :], u[None, :]]
            ok_blank = (t >= 1) & (t - 1 <= T - 1)      # t-1 in [0, T-1]
            from_blank = jnp.where(ok_blank[None, :], from_blank, NEG)
            te = jnp.clip(t, 0, T - 1)
            up = jnp.clip(u - 1, 0, U1 - 2)
            prev_emit = jnp.concatenate(
                [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            from_emit = prev_emit + emit_lp[bidx, te[None, :], up[None, :]]
            ok_emit = (u >= 1) & (t >= 0) & (t <= T - 1)
            from_emit = jnp.where(ok_emit[None, :], from_emit, NEG)
            new = jnp.logaddexp(from_blank, from_emit)
            return new, new

        ds = jnp.arange(1, T + U1 - 1)
        _, diags = jax.lax.scan(step, alpha0, ds)       # [D-1, B, U1]
        diags = jnp.concatenate([alpha0[None], diags], 0)  # [D, B, U1]
        d_final = jnp.clip(ilen - 1 + ulen, 0, T + U1 - 2)
        a_final = diags[d_final, jnp.arange(B), ulen]
        loss = -(a_final
                 + blank_lp[jnp.arange(B), jnp.clip(ilen - 1, 0, T - 1),
                            ulen])
        if reduction == "mean":
            return jnp.sum(loss) / B                     # reference: sum/B
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return dispatch.apply(fn, input, label, input_lengths, label_lengths,
                          op_name="rnnt_loss")
