"""Native (C++) runtime components, built on demand with g++ and bound via
ctypes (the reference's native layer uses pybind11 — paddle/fluid/pybind;
here the ABI surface is small C functions so ctypes suffices)."""
from .build import load_native  # noqa: F401
