#!/usr/bin/env python
"""Op-coverage manifest (N14 / L2 analog).

The reference generates its op surface from YAML manifests
(paddle/phi/api/yaml/ops.yaml + legacy_ops.yaml); this tool measures the
TPU framework's coverage AGAINST those manifests and writes
OPS_COVERAGE.json — a judgeable, regenerable inventory instead of a
hand-maintained claim.

Usage:  python tools/op_manifest.py [--ref /root/reference] [--out OPS_COVERAGE.json]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# reference op name -> our public name, for renames / fusions that exist
# under a different (jax-idiomatic) spelling
ALIASES = {
    "matmul": "matmul", "elementwise_add": "add", "elementwise_mul": "multiply",
    "elementwise_sub": "subtract", "elementwise_div": "divide",
    "elementwise_pow": "pow",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any", "arg_max": "argmax", "arg_min": "argmin",
    "fill_constant": "full", "top_k": "topk", "one_hot_v2": "one_hot",
    "softmax_with_cross_entropy": "cross_entropy",
    "cross_entropy_with_softmax": "cross_entropy",
    "sigmoid_cross_entropy_with_logits": "binary_cross_entropy_with_logits",
    "flash_attn": "flash_attention",
    "fused_adam_": "fused_adamw",
    "bce_loss": "binary_cross_entropy",
    "kldiv_loss": "kl_div",
    "logsigmoid": "log_sigmoid",
    "frobenius_norm": "norm",
    "fill": "fill_",
    "full_batch_size_like": "full",
    "full_int_array": "full",
    "uniform_inplace": "uniform_",
    "mean_all": "mean",
    "p_norm": "norm",
    "pad3d": "pad",
    "pool2d": "avg_pool2d",
    "pool3d": "avg_pool3d",
    "split_with_num": "split",
    "trans_layout": "transpose",
    "max_pool2d_with_index": "max_pool2d",
    "max_pool3d_with_index": "max_pool3d",
    "flash_attn_unpadded": "flash_attention",
    "assign_out_": "assign",
    "assign_value_": "assign",
    "copy_to": "clone",
    "linear_interp": "interpolate", "bilinear_interp": "interpolate",
    "trilinear_interp": "interpolate", "nearest_interp": "interpolate",
    "bicubic_interp": "interpolate",
}

# reference ops whose surface in this framework is a CLASS or module
# attribute rather than a flat function; each value is verified by
# attribute lookup at generation time
CLASS_COVERAGE = {
    "adam_": "optimizer.Adam", "adamw_": "optimizer.AdamW",
    "adamax_": "optimizer.Adamax", "adagrad_": "optimizer.Adagrad",
    "sgd_": "optimizer.SGD", "momentum_": "optimizer.Momentum",
    "rmsprop_": "optimizer.RMSProp", "lamb_": "optimizer.Lamb",
    "lars_momentum_": "distributed.fleet.meta_optimizers.LarsMomentum",
    "dgc_momentum": "distributed.fleet.meta_optimizers.DGCMomentum",
    "accuracy": "metric.Accuracy", "auc": "metric.Auc",
    "clip_by_norm": "nn.ClipGradByNorm",
    "check_finite_and_unscale_": "amp.GradScaler",
    "update_loss_scaling_": "amp.GradScaler",
    "check_numerics": "amp.debugging.check_numerics",
    "fft_c2c": "fft.fft", "fft_r2c": "fft.rfft", "fft_c2r": "fft.irfft",
    "depthwise_conv2d": "nn.functional.conv2d",
    "send_u_recv": "geometric.send_u_recv",
    "send_ue_recv": "geometric.send_ue_recv",
    "send_uv": "geometric.send_uv",
    "graph_send_recv": "geometric.send_u_recv",
    "segment_pool": "geometric.segment_sum",
    "dirichlet": "distribution.Dirichlet",
    "nms": "vision.ops.nms",
    "box_coder": "vision.ops.box_coder",
    "roi_align": "vision.ops.roi_align",
    "prior_box": "vision.ops.prior_box",
    "edit_distance": "vision.ops.edit_distance",
    "spectral_norm": "nn.SpectralNorm",
    "margin_cross_entropy": "nn.functional.margin_cross_entropy",
    "lookahead": "incubate.optimizer.LookAhead",
    "decode_jpeg": "vision.ops.decode_jpeg",
    "roi_pool": "vision.ops.roi_pool",
    "fill_diagonal": "fill_diagonal_",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "repeat_interleave_with_tensor_index": "ops.repeat_interleave",
    "npu_identity": "ops.clone",
    "rnn": "nn.RNN",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "viterbi_decode": "text.viterbi_decode",
    "temporal_shift": "nn.functional.temporal_shift",
    "unpool": "nn.functional.max_unpool2d",
    "matrix_rank_tol": "ops.linalg.matrix_rank",
    "warpctc": "nn.functional.ctc_loss",
    "memory_efficient_attention": "nn.functional.scaled_dot_product_attention",
    "merged_adam_": "optimizer.Adam",
    "merged_momentum_": "optimizer.Momentum",
    "adadelta_": "optimizer.Adadelta",
    "tanh_shrink": "nn.functional.tanhshrink",
    "grid_sample": "nn.functional.grid_sample",
    "affine_grid": "nn.functional.affine_grid",
    "channel_shuffle": "nn.functional.channel_shuffle",
    "huber_loss": "nn.functional.huber_loss",
    "log_loss": "nn.functional.log_loss",
}


def reference_ops(ref_root: str):
    ops = set()
    for name in ("ops.yaml", "legacy_ops.yaml"):
        path = os.path.join(ref_root, "paddle/phi/api/yaml", name)
        if not os.path.exists(path):
            continue
        for line in open(path, encoding="utf-8"):
            m = re.match(r"^- op\s*:\s*([a-zA-Z0-9_]+)", line)
            if m:
                ops.add(m.group(1))
    return ops


def our_surface():
    """Public callables on the op-bearing namespaces."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as pt

    names = set()
    spaces = [pt, pt.ops, pt.nn.functional, pt.linalg if hasattr(pt, "linalg")
              else pt.ops, pt.fft, pt.signal, pt.sparse, pt.geometric]
    for sp in spaces:
        for n in dir(sp):
            if n.startswith("_"):
                continue
            if callable(getattr(sp, n, None)):
                names.add(n)
    # pallas / fusion kernels
    from paddle_tpu.ops import pallas_kernels as pk

    for n in dir(pk):
        if not n.startswith("_"):
            names.add(n)
    try:
        from paddle_tpu.ops.pallas_kernels import flash_attention as fa  # noqa
        names.add("flash_attention")
    except Exception:
        pass
    from paddle_tpu.ops.pallas_kernels import fused_adamw  # noqa

    names.add("fused_adamw")
    return names


def _resolve_dotted(path):
    import paddle_tpu as pt

    obj = pt
    for part in path.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def classify(ref_ops, ours):
    covered, missing = {}, []
    for op in sorted(ref_ops):
        base = op[:-1] if op.endswith("_") else op  # inplace variants
        target = None
        for cand in (op, base, ALIASES.get(op), ALIASES.get(base)):
            if cand and cand in ours:
                target = cand
                break
        if target is None:
            dotted = CLASS_COVERAGE.get(op) or CLASS_COVERAGE.get(base)
            if dotted and _resolve_dotted(dotted) is not None:
                target = dotted
        if target:
            covered[op] = target
        else:
            missing.append(op)
    return covered, missing


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--out", default=os.path.join(REPO, "OPS_COVERAGE.json"))
    args = ap.parse_args()
    ref_ops = reference_ops(args.ref)
    ours = our_surface()
    covered, missing = classify(ref_ops, ours)
    doc = {
        "reference_manifest_ops": len(ref_ops),
        "covered": len(covered),
        "coverage_pct": round(100.0 * len(covered) / max(len(ref_ops), 1), 1),
        "our_public_callables": len(ours),
        "missing": missing,
        "covered_map": covered,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
    print(f"{doc['covered']}/{doc['reference_manifest_ops']} reference "
          f"manifest ops covered ({doc['coverage_pct']}%); "
          f"wrote {args.out}")


if __name__ == "__main__":
    main()
