"""Unified telemetry: metrics registry + host span tracing.

Two cooperating halves (docs/observability.md):

- :mod:`metrics` — a process-wide, lock-cheap registry of Counters,
  Gauges, and log-bucketed Histograms (labeled; JSON snapshot +
  Prometheus text exposition).  The serving engine's fault/shed/
  occupancy counters and the per-request SLO histograms (TTFT,
  inter-token latency, queue wait, end-to-end) live here.
- :mod:`trace` — a ring-buffered, thread-aware host span tracer
  (context manager + decorator) exporting Chrome-trace/Perfetto JSON,
  with each span nesting a ``jax.profiler.TraceAnnotation`` so host
  phases align with the device timeline when an XLA capture is active.

Both are import-light (no jax at import time) so the disabled path
stays near-zero; ``paddle_tpu.profiler`` is the user-facing facade.

``PADDLE_TPU_TRACE=1`` in the environment enables span tracing at
import (capacity via ``PADDLE_TPU_TRACE_CAPACITY``).
"""
from __future__ import annotations

import os as _os

from . import metrics, trace  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, CounterSet, Gauge, Histogram, Registry, registry,
)
from .trace import (  # noqa: F401
    Span, Tracer, active, disable, enable, export_chrome_trace, span,
    summarize, traced,
)

__all__ = [
    "metrics", "trace",
    "Counter", "CounterSet", "Gauge", "Histogram", "Registry", "registry",
    "Span", "Tracer", "active", "disable", "enable", "export_chrome_trace",
    "span", "summarize", "traced",
]

if _os.environ.get("PADDLE_TPU_TRACE", "") not in ("", "0", "false", "False"):
    enable(capacity=int(_os.environ.get("PADDLE_TPU_TRACE_CAPACITY",
                                        "65536")))
