"""Base gate (reference gate/base_gate.py)."""
from ......nn.layer import Layer


class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = num_expert * world_size
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss
