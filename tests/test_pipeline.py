"""SPMD pipeline-parallel tests (reference:
test/collective/fleet/hybrid_parallel_pp_transformer.py — multi-process
1F1B; here the pipeline is one SPMD program over the 'pp' mesh axis)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import mesh as M
from paddle_tpu.distributed.fleet.meta_parallel import pp_spmd
from paddle_tpu.models import (
    GPTPretrainingCriterion,
    GPTStackedForPretraining,
    gpt_tiny,
)


@pytest.fixture
def pp_mesh():
    prev = M._global_mesh
    mesh = M.build_mesh({"dp": 2, "pp": 4})
    M.set_mesh(mesh)
    yield mesh
    M._global_mesh = prev


@pytest.fixture
def no_mesh():
    prev = M._global_mesh
    M._global_mesh = None
    yield
    M._global_mesh = prev


def _toy_block():
    def block(params, h):
        (w,) = params
        return jnp.tanh(h @ w)
    return block


def test_pipeline_blocks_matches_scan(pp_mesh):
    L, h, mbs, mb, s = 8, 16, 4, 2, 12
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(L, h, h).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(mbs, mb, s, h).astype(np.float32))
    block = _toy_block()
    ref = jax.vmap(lambda xm: pp_spmd.scan_blocks(block, (W,), xm))(x)
    Wp = jax.device_put(W, pp_spmd.stacked_param_sharding(W.shape))
    out = pp_spmd.pipeline_blocks(block, (Wp,), x, layers_per_stage=L // 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)


@pytest.mark.slow
def test_pipeline_blocks_grad_matches(pp_mesh):
    L, h, mbs, mb, s = 4, 8, 4, 2, 6
    rng = np.random.RandomState(1)
    W = jnp.asarray(rng.randn(L, h, h).astype(np.float32) * 0.2)
    x = jnp.asarray(rng.randn(mbs, mb, s, h).astype(np.float32))
    block = _toy_block()

    def loss_pipe(W):
        return jnp.sum(pp_spmd.pipeline_blocks(block, (W,), x, layers_per_stage=1) ** 2)

    def loss_ref(W):
        return jnp.sum(jax.vmap(lambda xm: pp_spmd.scan_blocks(block, (W,), xm))(x) ** 2)

    g1 = jax.grad(loss_pipe)(jax.device_put(W, pp_spmd.stacked_param_sharding(W.shape)))
    g2 = jax.grad(loss_ref)(W)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-7)


@pytest.mark.slow
def test_gpt_stacked_pipeline_matches_single_device(no_mesh):
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0, num_layers=4)
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
    lbl = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
    crit = GPTPretrainingCriterion(cfg)

    pt.seed(3)
    m1 = GPTStackedForPretraining(cfg)
    ref = float(crit(m1(ids), lbl))

    mesh = M.build_mesh({"dp": 2, "pp": 4})
    M.set_mesh(mesh)
    try:
        pt.seed(3)
        m2 = GPTStackedForPretraining(cfg, n_micro=2)
        loss = crit(m2(ids), lbl)
        assert abs(float(loss) - ref) < 1e-4
        loss.backward()
        g = m2.decoder.qkv_w.grad
        assert g is not None and np.isfinite(g.numpy()).all()
    finally:
        M._global_mesh = None


def test_gpt_stacked_trains(no_mesh):
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0, num_layers=2)
    pt.seed(5)
    m = GPTStackedForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)), dtype="int64")
    lbl = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)), dtype="int64")
    losses = []
    for _ in range(4):
        loss = crit(m(ids), lbl)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_dryrun_multichip_with_pp():
    import __graft_entry__ as g

    prev = M._global_mesh
    try:
        g.dryrun_multichip(8)
    finally:
        M._global_mesh = prev


def test_pipeline_interleave_matches_scan(pp_mesh):
    """Virtual-stage interleave (reference PipelineParallelWithInterleave,
    pipeline_parallel.py:625): V=2 chunks per device, Megatron round-robin
    chunk->device layout, M >= S microbatches."""
    L, h, mbs, mb, s = 16, 8, 8, 2, 6  # S=4, V=2 -> lpc=2
    rng = np.random.RandomState(2)
    W = jnp.asarray(rng.randn(L, h, h).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(mbs, mb, s, h).astype(np.float32))
    block = _toy_block()
    ref = jax.vmap(lambda xm: pp_spmd.scan_blocks(block, (W,), xm))(x)
    out = pp_spmd.pipeline_blocks(block, (W,), x, layers_per_stage=L // 4,
                                  n_virtual=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_pipeline_interleave_grad_matches(pp_mesh):
    L, h, mbs, mb, s = 8, 8, 4, 2, 6  # S=4, V=2, lpc=1
    rng = np.random.RandomState(3)
    W = jnp.asarray(rng.randn(L, h, h).astype(np.float32) * 0.2)
    x = jnp.asarray(rng.randn(mbs, mb, s, h).astype(np.float32))
    block = _toy_block()

    def loss_pipe(W):
        return jnp.sum(pp_spmd.pipeline_blocks(
            block, (W,), x, layers_per_stage=2, n_virtual=2) ** 2)

    def loss_ref(W):
        return jnp.sum(jax.vmap(lambda xm: pp_spmd.scan_blocks(block, (W,), xm))(x) ** 2)

    g1 = jax.grad(loss_pipe)(W)
    g2 = jax.grad(loss_ref)(W)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_gpt_stacked_interleave_trains(no_mesh):
    """GPT stacked decoder with virtual_pp_degree=2 on a pp mesh trains.

    slow: the interleaved wavefront fwd+bwd is one huge XLA graph on the
    8-device CPU mesh (>10 min compile); the fast set covers interleave
    correctness via test_pipeline_interleave_{matches_scan,grad_matches}."""
    prev = M._global_mesh
    try:
        mesh = M.build_mesh({"pp": 2, "dp": 2})
        M.set_mesh(mesh)
        cfg = gpt_tiny(num_layers=4, hidden_dropout=0.0, attention_dropout=0.0,
                       virtual_pp_degree=2)
        pt.seed(0)
        model = GPTStackedForPretraining(cfg, n_micro=2)
        crit = GPTPretrainingCriterion(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
        rng = np.random.RandomState(0)
        ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
        labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
        losses = []
        for _ in range(4):
            loss = crit(model(ids), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
    finally:
        M._global_mesh = prev


class TestFleetPipelineParallel:
    """fleet-API 1F1B runtime (reference pipeline_parallel.py:229):
    train_batch must actually schedule per-stage fwd/bwd with bounded
    activation residency and match plain gradient accumulation."""

    def _build(self, n_stages, lr=0.0):
        from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
            LayerDesc, PipelineLayer, PipelineParallel,
        )
        from paddle_tpu.nn.modules.common import Linear

        pt.seed(7)
        descs = [LayerDesc(Linear, 8, 8) for _ in range(4)]

        def loss_fn(out, y):
            return pt.ops.mean((out - y) ** 2)

        pl = PipelineLayer(descs, num_stages=n_stages, loss_fn=loss_fn)

        class Strat:
            pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}

        pp = PipelineParallel(pl, strategy=Strat())
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
        return pp, pl, opt

    def test_1f1b_matches_plain_accumulation(self):
        rng = np.random.RandomState(0)
        xb = rng.randn(8, 8).astype(np.float32)
        yb = rng.randn(8, 8).astype(np.float32)

        # pipelined (2 stages)
        pp, pl, opt = self._build(2)
        loss_pp = pp.train_batch(
            (pt.to_tensor(xb), pt.to_tensor(yb)), opt)
        w_pp = [p.numpy().copy() for p in pl.parameters()]

        # plain accumulation reference (1 stage == sequential)
        pp1, pl1, opt1 = self._build(1)
        loss_1 = pp1.train_batch(
            (pt.to_tensor(xb), pt.to_tensor(yb)), opt1)
        w_1 = [p.numpy().copy() for p in pl1.parameters()]

        np.testing.assert_allclose(float(loss_pp), float(loss_1), rtol=1e-5)
        for a, b in zip(w_pp, w_1):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_1f1b_activation_residency_bound(self):
        """At most S micro-batches in flight (1F1B), not all M (GPipe)."""
        rng = np.random.RandomState(1)
        xb = rng.randn(8, 8).astype(np.float32)
        yb = rng.randn(8, 8).astype(np.float32)
        pp, pl, opt = self._build(2)
        pp.train_batch((pt.to_tensor(xb), pt.to_tensor(yb)), opt)
        assert pp.accumulate_steps == 4  # M
        assert pp.last_peak_inflight == 2  # == S, < M

    def test_grad_scaler_path(self):
        from paddle_tpu.amp import GradScaler

        rng = np.random.RandomState(2)
        xb = rng.randn(8, 8).astype(np.float32)
        yb = rng.randn(8, 8).astype(np.float32)
        pp, pl, opt = self._build(2)
        scaler = GradScaler(init_loss_scaling=256.0)
        loss = pp.train_batch((pt.to_tensor(xb), pt.to_tensor(yb)), opt,
                              scaler=scaler)
        assert np.isfinite(float(loss))


@pytest.mark.slow
def test_fleet_api_gpt_tp2_pp2_trains():
    """BASELINE config 2 analog (reference
    test/collective/fleet/hybrid_parallel_pp_transformer.py): GPT built as
    a PipelineLayer of TP (mpu) blocks, wrapped by fleet.distributed_model
    into PipelineParallel, trained with train_batch on a dp1 x pp2 x mp2
    mesh — losses must be finite and descend."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        LayerDesc, PipelineLayer,
    )
    from paddle_tpu.models.gpt import (
        GPTDecoderLayer, GPTEmbeddings, GPTPretrainingCriterion, gpt_tiny,
    )
    from paddle_tpu.nn.modules.norm import LayerNorm

    prev = M._global_mesh
    try:
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "pp_degree": 2, "mp_degree": 2,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        fleet.init(is_collective=True, strategy=strategy)
        cfg = gpt_tiny(use_tensor_parallel=True, num_layers=4,
                       hidden_dropout=0.0, attention_dropout=0.0)
        pt.seed(0)

        class Head(pt.nn.Layer):
            def __init__(self, emb):
                super().__init__()
                self._emb = emb

            def forward(self, h):
                return pt.ops.matmul(h, self._emb.word_embeddings.weight,
                                     transpose_y=True)

        emb = GPTEmbeddings(cfg)
        crit = GPTPretrainingCriterion(cfg)
        descs = [emb] + [GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)]
        descs += [LayerNorm(cfg.hidden_size), Head(emb)]

        def loss_fn(logits, labels):
            return crit(logits, labels)

        pl = PipelineLayer(descs, num_stages=2, loss_fn=loss_fn)
        strategy.pipeline_configs = {"accumulate_steps": 2, "micro_batch_size": 2}
        model = fleet.distributed_model(pl)
        opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=pl.parameters())
        opt = fleet.distributed_optimizer(opt)

        rng = np.random.RandomState(0)
        ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
        labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)), dtype="int64")
        losses = [float(model.train_batch((ids, labels), opt)) for _ in range(4)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
        assert model.last_peak_inflight <= 2
    finally:
        M._global_mesh = prev


@pytest.mark.slow
def test_multiprocess_launch_both_nodes(tmp_path):
    """Run both 'nodes' concurrently via the launcher (auto-rank
    rendezvous) and assert both workers succeed."""
    import subprocess, sys, os, time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] + ["/root/repo"])
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PADDLE_TPU_NO_JAX_DIST"] = "1"
    import random

    port = random.randint(20000, 50000)  # avoid cross-run port residue
    procs = []
    for node in range(2):
        log_dir = str(tmp_path / f"logs{node}")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}", "--rank", "auto",
             "--log_dir", log_dir,
             "tests/launch_worker_fixture.py"],
            cwd="/root/repo", env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    rcs = [p.wait(timeout=300) for p in procs]
    assert rcs == [0, 0], [p.stdout.read().decode()[-2000:] for p in procs]
    logs = ""
    for node in range(2):
        d = tmp_path / f"logs{node}"
        for f in d.glob("workerlog.*"):
            logs += f.read_text()
    assert logs.count("WORKER_OK") == 2, logs[-2000:]


def test_hybrid_optimizer_global_clip():
    """The docstring's claim: ClipGradByGlobalNorm through
    HybridParallelOptimizer computes the GLOBAL norm over all (sharded)
    params — matching a hand-computed global norm."""
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_optimizer import (
        HybridParallelOptimizer,
    )

    prev = M._global_mesh
    try:
        M.set_mesh(M.build_mesh({"mp": 4, "dp": 2}))
        pt.seed(13)
        from paddle_tpu.ops.sharding_ops import shard_param

        w1 = pt.to_tensor(np.ones((8, 4), np.float32), stop_gradient=False)
        w2 = pt.to_tensor(np.ones((4,), np.float32) * 2, stop_gradient=False)
        shard_param(w1, "mp", None)  # mp-sharded like a TP weight
        clip = pt.nn.ClipGradByGlobalNorm(clip_norm=1.0)
        inner = pt.optimizer.SGD(learning_rate=1.0, parameters=[w1, w2],
                                 grad_clip=clip)
        opt = HybridParallelOptimizer(inner)
        w1.grad = pt.to_tensor(np.full((8, 4), 3.0, np.float32))
        w2.grad = pt.to_tensor(np.full((4,), 4.0, np.float32))
        before1, before2 = w1.numpy().copy(), w2.numpy().copy()
        opt.step()
        gnorm = np.sqrt((3.0**2) * 32 + (4.0**2) * 4)  # global, both params
        np.testing.assert_allclose(
            before1 - w1.numpy(), 3.0 / gnorm, rtol=1e-5)
        np.testing.assert_allclose(
            before2 - w2.numpy(), 4.0 / gnorm, rtol=1e-5)
    finally:
        M._global_mesh = prev
