"""TensorParallel wrapper (reference: fleet/meta_parallel/tensor_parallel.py:27
— broadcasts non-TP params across the mp group and wires TP layers).
TPU-native: parameters are born in their NamedSharding layouts (the mpu
layers shard themselves), so the wrapper only constrains inputs to be
replicated over 'mp' and batch-sharded over 'dp'."""
from __future__ import annotations

from ....nn.layer import Layer
from ....ops.sharding_ops import shard_constraint
from ....tensor import Tensor
from ... import mesh as _mesh


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        if _mesh.has_mesh() and "dp" in _mesh.get_mesh().axis_names:
            inputs = tuple(
                shard_constraint(x, "dp") if isinstance(x, Tensor) else x
                for x in inputs
            )
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
