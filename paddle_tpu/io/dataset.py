"""Datasets (reference: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no length")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, tuple):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        s = 0
        for d in self.datasets:
            s += len(d)
            self.cumulative_sizes.append(s)

    def __len__(self):
        return self.cumulative_sizes[-1] if self.cumulative_sizes else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        lo = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - lo]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np

    n = len(dataset)
    if sum(lengths) != n:
        # fraction support
        if all(0 < l < 1 for l in lengths):
            lengths = [int(l * n) for l in lengths]
            lengths[-1] = n - sum(lengths[:-1])
        else:
            raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(n)
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out
