"""Autoregressive decode engine: KV-cache correctness vs the full-context
forward, retrace-freedom (trace counters), sampling (greedy / top-k /
top-p), donated-cache memory flatness, the Predictor decode mode — plus
the PR's satellite regressions (clear_grad(set_to_zero), DataLoader
prefetch-producer shutdown)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import (
    GPTForPretraining,
    GPTStackedForPretraining,
    generation,
    gpt_tiny,
)


def _tiny_cfg():
    return gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)


def _prompt(cfg, b=2, s=6, seed=0):
    rng = np.random.RandomState(seed)
    return pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)), dtype="int64")


# ---------------------------------------------------------------------------
# KV-cache decode correctness vs the no-cache forward
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cache_dtype,atol", [("float32", 5e-5),
                                              ("bfloat16", 0.08)])
def test_cached_decode_matches_full_forward_layered(cache_dtype, atol):
    """Eager prefill + per-token decode through the cache reproduce the
    full-context logits (fp32 cache: numerically tight; bf16 cache: within
    the K/V rounding)."""
    pt.seed(0)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg, s=12)
    full = m(ids).numpy()
    cache = m.new_kv_cache(2, 64, dtype=cache_dtype)
    pre = m(ids[:, :8], kv_cache=cache, cache_index=0).numpy()
    np.testing.assert_allclose(pre, full[:, :8], rtol=1e-2, atol=atol)
    for t in range(8, 12):
        step = m(ids[:, t:t + 1], kv_cache=cache, cache_index=t).numpy()
        np.testing.assert_allclose(step[:, 0], full[:, t], rtol=1e-2,
                                   atol=atol)


def test_cached_decode_matches_full_forward_stacked():
    """Same contract on the stacked decoder: the [L, ...] cache scans
    alongside the stacked parameters."""
    pt.seed(3)
    cfg = _tiny_cfg()
    m = GPTStackedForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg, s=10, seed=1)
    full = m(ids).numpy()
    cache = m.new_kv_cache(2, 64, dtype="float32")
    pre = m(ids[:, :6], kv_cache=cache, cache_index=0).numpy()
    np.testing.assert_allclose(pre, full[:, :6], rtol=1e-4, atol=5e-5)
    for t in range(6, 10):
        step = m(ids[:, t:t + 1], kv_cache=cache, cache_index=t).numpy()
        np.testing.assert_allclose(step[:, 0], full[:, t], rtol=1e-4,
                                   atol=5e-5)


@pytest.mark.parametrize("model_cls", [GPTForPretraining,
                                       GPTStackedForPretraining])
def test_chunked_prefill_matches_full_forward(model_cls):
    """S>1 prefill at a NONZERO position must see the earlier chunks
    through the cache (general masked path), not just attend to itself."""
    pt.seed(21)
    cfg = _tiny_cfg()
    m = model_cls(cfg)
    m.eval()
    ids = _prompt(cfg, s=12, seed=3)
    full = m(ids).numpy()
    cache = m.new_kv_cache(2, 64, dtype="float32")
    m(ids[:, :4], kv_cache=cache, cache_index=0)
    mid = m(ids[:, 4:9], kv_cache=cache, cache_index=4).numpy()
    np.testing.assert_allclose(mid, full[:, 4:9], rtol=1e-4, atol=5e-5)
    tail = m(ids[:, 9:12], kv_cache=cache, cache_index=9).numpy()
    np.testing.assert_allclose(tail, full[:, 9:12], rtol=1e-4, atol=5e-5)


@pytest.mark.parametrize("model_cls", [GPTForPretraining,
                                       GPTStackedForPretraining])
def test_generate_greedy_logits_match_full_forward(model_cls):
    """generate()'s per-step logits equal the no-cache forward over the
    (prompt + generated) sequence — greedy, so the token streams agree."""
    pt.seed(7)
    cfg = _tiny_cfg()
    m = model_cls(cfg)
    m.eval()
    ids = _prompt(cfg)
    out, logits = m.generate(ids, max_new_tokens=8, max_seq_len=64,
                             cache_dtype="float32", return_logits=True)
    assert out.shape == [2, 6 + 8]
    assert np.array_equal(out.numpy()[:, :6], ids.numpy())
    full = m(out).numpy()
    gl = logits.numpy()
    for i in range(8):
        np.testing.assert_allclose(gl[:, i], full[:, 5 + i], rtol=1e-4,
                                   atol=5e-5)
    # greedy consistency: each emitted token is the argmax of its logits
    assert np.array_equal(out.numpy()[:, 6:], gl.argmax(-1))


def test_generate_greedy_deterministic():
    pt.seed(11)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    a = m.generate(ids, max_new_tokens=6, max_seq_len=64,
                   cache_dtype="float32").numpy()
    b = m.generate(ids, max_new_tokens=6, max_seq_len=64,
                   cache_dtype="float32").numpy()
    assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# retrace-freedom: N decode steps compile at most twice (prefill + decode)
# ---------------------------------------------------------------------------

def test_decode_trace_counter_64_tokens():
    """The step bodies execute only while tracing (scout + jit trace = 2
    runs per compiled program).  A 64-token decode — and a whole second
    generate() — must compile at most twice (prefill + decode) and never
    retrace after the first decode step."""
    pt.seed(5)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    generation.reset_trace_counts()
    m.generate(ids, max_new_tokens=64, max_seq_len=128, cache_dtype="float32")
    counts = generation.trace_counts()
    # at most one compile each => at most 2 python-body executions each
    assert counts["prefill"] <= 2 and counts["decode"] <= 2, counts
    m.generate(ids, max_new_tokens=64, max_seq_len=128, cache_dtype="float32")
    assert generation.trace_counts() == counts
    eng = m.__dict__["_decode_engines"][(2, 128, "float32", False, 0, False)]
    assert eng.compiled_programs == 2  # prefill + decode, nothing else


def test_decode_memory_flat_across_steps():
    """Donated-cache invariant: framework-visible memory does not grow with
    the number of decode steps (each step aliases the cache update)."""
    from paddle_tpu.core import memory as pt_memory

    pt.seed(6)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    one = pt.to_tensor(np.float32(1.0))
    eng = generation._engine_for(m, 2, 64, "float32", do_sample=False,
                                 top_k=0, use_top_p=False)
    tok, _ = eng.prefill(ids, one, one)
    pos = pt.to_tensor(np.int32(6))
    tok, pos, _ = eng.decode(tok, pos, one, one)
    before = pt_memory.memory_allocated()
    for _ in range(20):
        tok, pos, _ = eng.decode(tok, pos, one, one)
    after = pt_memory.memory_allocated()
    # per-step residue would be >= one [B, V] logits buffer per step; allow
    # only sub-single-buffer noise
    assert after - before < 2 * 1024 * np.dtype(np.float32).itemsize, (
        before, after)


# ---------------------------------------------------------------------------
# sampling: top-k / top-p filtering and reproducibility
# ---------------------------------------------------------------------------

def test_filter_logits_top_k_support():
    logits = pt.to_tensor(np.array([[0., 1., 2., 3., 4.],
                                    [4., 3., 2., 1., 0.]], np.float32))
    f = generation.filter_logits(logits, top_k=2).numpy()
    kept = f > -1e29
    assert kept.sum(axis=1).tolist() == [2, 2]
    assert kept[0].tolist() == [False, False, False, True, True]
    assert kept[1].tolist() == [True, True, False, False, False]


def test_filter_logits_top_p_mass():
    """Nucleus filter keeps the smallest prefix reaching mass p and the
    kept set renormalizes to >= p (always at least the argmax)."""
    raw = np.array([[0., 1., 2., 3., 4.]], np.float32)
    probs = np.exp(raw[0]) / np.exp(raw[0]).sum()
    logits = pt.to_tensor(raw)
    # p=0.6: the argmax alone carries ~0.636 >= 0.6 -> keep exactly it
    f = generation.filter_logits(
        logits, top_p=pt.to_tensor(np.float32(0.6))).numpy()
    assert (f > -1e29).tolist() == [[False, False, False, False, True]]
    # p=0.8: top-1 (0.636) < 0.8, top-2 (0.87) >= 0.8 -> keep two
    f = generation.filter_logits(
        logits, top_p=pt.to_tensor(np.float32(0.8))).numpy()
    kept = f > -1e29
    assert kept.sum() == 2
    assert probs[kept[0]].sum() >= 0.8


def test_sample_tokens_stay_in_top_k_support():
    logits = pt.to_tensor(
        np.array([[0.0, 5.0, 1.0, 4.0, 2.0, 3.0, -1.0, 0.5]], np.float32))
    pt.seed(123)
    seen = set()
    for _ in range(64):
        tok = generation.sample_tokens(
            logits, do_sample=True,
            temperature=pt.to_tensor(np.float32(1.0)), top_k=3)
        seen.add(int(tok.numpy()[0]))
    assert seen <= {1, 3, 5}, seen   # the top-3 ids
    assert len(seen) > 1             # and it actually samples


def test_generate_sampling_reproducible_and_in_vocab():
    cfg = _tiny_cfg()
    pt.seed(0)
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    pt.seed(42)
    a = m.generate(ids, max_new_tokens=6, do_sample=True, temperature=0.8,
                   top_k=50, top_p=0.9, max_seq_len=64,
                   cache_dtype="float32").numpy()
    pt.seed(42)
    b = m.generate(ids, max_new_tokens=6, do_sample=True, temperature=0.8,
                   top_k=50, top_p=0.9, max_seq_len=64,
                   cache_dtype="float32").numpy()
    assert np.array_equal(a, b)
    assert (a >= 0).all() and (a < cfg.vocab_size).all()


def test_generate_eos_padding():
    """Rows freeze at their first eos: every position after it is eos."""
    pt.seed(9)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    base = m.generate(ids, max_new_tokens=6, max_seq_len=64,
                      cache_dtype="float32").numpy()
    eos = int(base[0, 6 + 2])  # whatever greedy emits at step 2 of row 0
    out = m.generate(ids, max_new_tokens=6, eos_token_id=eos, max_seq_len=64,
                     cache_dtype="float32").numpy()
    gen = out[:, 6:]
    for row in gen:
        hits = np.nonzero(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all()


def test_decode_engine_cache_is_lru_bounded():
    """Each engine pins a KV cache in HBM: distinct request shapes must
    not accumulate past the bound, and reuse must refresh recency."""
    pt.seed(14)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    for b in (16, 24, 32, 40, 48):   # five distinct max_seq_len keys
        m.generate(ids, max_new_tokens=2, max_seq_len=b + 16,
                   cache_dtype="float32")
    engines = m.__dict__["_decode_engines"]
    assert len(engines) == generation._MAX_ENGINES
    assert (2, 32, "float32", False, 0, False) not in engines  # evicted
    m.clear_decode_cache()
    assert "_decode_engines" not in m.__dict__


def test_cache_path_rejects_attn_mask():
    """The KV-cache path is causal+length-masked; a user-supplied mask
    (left padding) must fail loudly, not be silently dropped."""
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    cache = m.new_kv_cache(2, 64, dtype="float32")
    mask = pt.to_tensor(np.ones((2, 1, 6, 6), np.float32))
    with pytest.raises(ValueError, match="KV-cache path"):
        m(ids, attn_mask=mask, kv_cache=cache, cache_index=0)


def test_generate_validates_lengths():
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    ids = _prompt(cfg)
    with pytest.raises(ValueError, match="exceeds the"):
        m.generate(ids, max_new_tokens=60, max_seq_len=64,
                   cache_dtype="float32")
    with pytest.raises(ValueError, match="max_position_embeddings"):
        m.generate(ids, max_new_tokens=4, max_seq_len=4096)


# ---------------------------------------------------------------------------
# pallas kernel parity (interpreter on CPU; the real kernel on TPU)
# ---------------------------------------------------------------------------

def test_decode_attention_kernel_parity_interpret():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_kernels import decode_attention as da

    rng = np.random.RandomState(0)
    B, H, S, D = 2, 2, 256, 64
    assert da.decode_shape_supported(S, D)
    for dt in (jnp.float32, jnp.bfloat16):
        q = jnp.array(rng.randn(B, H, D), dt)
        k = jnp.array(rng.randn(B, H, S, D), dt)
        v = jnp.array(rng.randn(B, H, S, D), dt)
        for length in (1, 127, 128, 256):
            ref = np.asarray(da._xla_decode_reference(
                q, k, v, jnp.int32(length), 0.125), np.float32)
            q8 = jnp.broadcast_to(q.reshape(B * H, 1, D), (B * H, 8, D))
            out = da._decode_pallas(
                q8, k.reshape(B * H, S, D), v.reshape(B * H, S, D),
                jnp.int32(length), 0.125, interpret=True)
            got = np.asarray(out[:, 0, :].reshape(B, H, D), np.float32)
            tol = 5e-6 if dt == jnp.float32 else 1e-2
            np.testing.assert_allclose(got, ref, rtol=tol, atol=tol)


@pytest.mark.skipif(
    __import__("jax").devices()[0].platform != "tpu",
    reason="real-kernel parity needs a TPU backend (tools/tpu_smoke.py)")
def test_decode_attention_kernel_parity_tpu():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_kernels import decode_attention as da

    rng = np.random.RandomState(0)
    B, H, S, D = 2, 4, 512, 64
    q = jnp.array(rng.randn(B, H, D), jnp.bfloat16)
    k = jnp.array(rng.randn(B, H, S, D), jnp.bfloat16)
    v = jnp.array(rng.randn(B, H, S, D), jnp.bfloat16)
    for length in (1, 5, 127, 128, 200, 512):
        got = np.asarray(da.decode_attention(q, k, v, jnp.int32(length)),
                         np.float32)
        ref = np.asarray(da._xla_decode_reference(
            q, k, v, jnp.int32(length), 0.125), np.float32)
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)


def test_decode_shape_eligibility_gate():
    from paddle_tpu.ops.pallas_kernels.decode_attention import (
        decode_shape_supported,
    )

    assert decode_shape_supported(128, 64)
    assert decode_shape_supported(2048, 128)
    assert not decode_shape_supported(64, 64)     # too short
    assert not decode_shape_supported(200, 64)    # not a 128 multiple
    assert not decode_shape_supported(256, 80)    # head dim not 64-multiple


# ---------------------------------------------------------------------------
# inference.Predictor causal-LM decode mode
# ---------------------------------------------------------------------------

def test_predictor_causal_lm_decode_mode():
    from paddle_tpu import inference

    pt.seed(2)
    cfg = _tiny_cfg()
    m = GPTForPretraining(cfg)
    m.eval()
    ids = _prompt(cfg)
    ref = m.generate(ids, max_new_tokens=5, max_seq_len=64,
                     cache_dtype="float32").numpy()

    config = inference.Config()
    config.set_causal_lm_model(m)
    config.enable_causal_lm_decode(max_new_tokens=5, max_seq_len=64,
                                   cache_dtype="float32")
    assert "causal_lm_decode" in config.summary()
    predictor = inference.create_predictor(config)
    h = predictor.get_input_handle(predictor.get_input_names()[0])
    h.copy_from_cpu(ids.numpy())
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    assert np.array_equal(out, ref)


def test_predictor_decode_mode_requires_live_model(tmp_path):
    from paddle_tpu import inference

    config = inference.Config(str(tmp_path / "nope"))
    config.enable_causal_lm_decode(max_new_tokens=2)
    with pytest.raises(RuntimeError, match="live model"):
        inference.create_predictor(config)


def test_predictor_live_model_requires_explicit_decode_opts():
    """A live model alone must not silently decode with hidden defaults."""
    from paddle_tpu import inference

    m = GPTForPretraining(_tiny_cfg())
    config = inference.Config().set_causal_lm_model(m)
    with pytest.raises(RuntimeError, match="enable_causal_lm_decode"):
        inference.create_predictor(config)


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------

def test_clear_grad_set_to_zero():
    """clear_grad(set_to_zero=True) must WRITE zeros (accumulation target
    stays bound), not silently behave like set_to_zero=False."""
    pt.seed(1)
    lin = pt.nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = pt.to_tensor(np.ones((3, 4), np.float32))

    lin(x).sum().backward()
    assert all(p.grad is not None for p in lin.parameters())
    g0 = {id(p): p.grad.numpy().copy() for p in lin.parameters()}
    held = {id(p): p.grad for p in lin.parameters()}  # cached handles

    opt.clear_grad(set_to_zero=True)
    for p in lin.parameters():
        assert p.grad is not None, "set_to_zero must keep the grad bound"
        assert p.grad is held[id(p)], "zeroing must be in place"
        assert not np.any(p.grad.numpy())
    # backward accumulates INTO the zeroed grad -> same as a fresh grad
    lin(x).sum().backward()
    for p in lin.parameters():
        np.testing.assert_allclose(p.grad.numpy(), g0[id(p)], rtol=1e-6)

    opt.clear_grad()  # default: unbind
    assert all(p.grad is None for p in lin.parameters())


def test_dataloader_prefetch_producer_shutdown_on_early_break():
    """A consumer that stops iterating early must release the prefetch
    producer thread (it used to park forever on q.put)."""
    from paddle_tpu.io import DataLoader, Dataset

    class Ds(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return np.full((4,), i, np.float32)

    before = set(threading.enumerate())
    loader = DataLoader(Ds(), batch_size=2, use_buffer_reader=True,
                        prefetch_factor=2)
    it = iter(loader)
    next(it)
    next(it)
    it.close()  # early break: generator finalization
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"prefetch producer leaked: {leaked}"

    # and a full pass still yields every batch exactly once
    vals = [b.numpy()[0, 0] for b in DataLoader(
        Ds(), batch_size=2, use_buffer_reader=True, prefetch_factor=2)]
    assert len(vals) == 32
