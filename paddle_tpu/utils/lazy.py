"""reference python/paddle/utils/lazy_import.py try_import."""
from __future__ import annotations

import importlib

__all__ = ["try_import"]


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed")
