"""On-demand native build: compile <name>.cpp into a cached shared object
and load it with ctypes. Analog of the reference's CMake native build,
scaled to this repo's small C-ABI surface."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_cache: dict = {}

_DIR = os.path.dirname(os.path.abspath(__file__))


def load_native(name: str):
    """Compile (if needed) and dlopen paddle_tpu/core/native/<name>.cpp.
    Returns a ctypes.CDLL, or None when no C++ toolchain is available."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache_dir = os.environ.get(
            "PADDLE_TPU_NATIVE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "native"))
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"{name}-{digest}.so")
        if not os.path.exists(so_path):
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   src, "-o", so_path + ".tmp"]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(so_path + ".tmp", so_path)
            except (OSError, subprocess.SubprocessError):
                _cache[name] = None
                return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError:
            lib = None
        _cache[name] = lib
        return lib
