"""paddle.text parity (reference: python/paddle/text/__init__.py exposing
the text datasets).  Zero-egress build: datasets parse canonical LOCAL
files and raise clearly when absent."""
from .datasets import Imdb, UCIHousing  # noqa: F401

__all__ = ["Imdb", "UCIHousing"]
