"""Quantization: QAT (fake-quant in the graph) + PTQ (observer-calibrated).

Reference: python/paddle/quantization/ — QuantConfig (config.py), QAT
(qat.py), PTQ (ptq.py), observers (observers/abs_max.py), quanters
(quanters/abs_max.py FakeQuanterWithAbsMaxObserver), wrapper.py.

TPU-native design: fake-quant is a pure jax expression with a
straight-through estimator (jax.lax.stop_gradient identity trick), so a
QAT model still compiles into ONE fused XLA program under jit.to_static
— no per-op observer kernels like the reference's CUDA fake_quant ops.
int8 simulated quantization only (TPU int8 matmuls arrive via XLA when
the pattern matches).
"""
from .config import QuantConfig  # noqa: F401
from .int8 import (  # noqa: F401
    Int8Linear, quantize_for_serving, quantized_matmul,
)
from .kv import TINY_SCALE, dequant_pages, quantize_kv_write  # noqa: F401
from .observers import AbsmaxObserver, AVGObserver, BaseObserver  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .qat import QAT  # noqa: F401
from .quanters import BaseQuanter, FakeQuanterWithAbsMaxObserver  # noqa: F401

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "BaseObserver", "AbsmaxObserver", "AVGObserver",
    "BaseQuanter", "FakeQuanterWithAbsMaxObserver",
    "quantized_matmul", "Int8Linear", "quantize_for_serving",
    "quantize_kv_write", "dequant_pages", "TINY_SCALE",
]
