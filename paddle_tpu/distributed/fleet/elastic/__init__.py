"""Elastic training: membership, failure detection, restart.

Reference: python/paddle/distributed/fleet/elastic/manager.py:124
(ElasticManager: ranks register under an etcd prefix with TTL leases,
heartbeat thread :254-268, watch membership host_call_back:240; the
launcher relaunches workers with a rescaled spec on change, bounded by
--max_restart).

TPU-native redesign: the KV substrate is the job's native TCPStore (no
etcd in the image).  Each node heartbeats by INCREMENTING a store-side
counter ``elastic/beat/<rank>`` — liveness is "the counter moved within
the last TTL seconds of the WATCHER's clock", so detection never
compares wall clocks across hosts (cross-host clock skew > TTL would
otherwise mark healthy nodes dead).  On membership change the manager
invokes the restart callback (the launcher's relaunch path) — the same
contract the reference's ElasticManager has with
launch/controllers/master.py.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, store, rank: int, nnodes: int,
                 min_nodes: Optional[int] = None,
                 max_nodes: Optional[int] = None,
                 ttl: float = 10.0, interval: float = 2.0,
                 on_change: Optional[Callable[[List[int]], None]] = None):
        self._store = store
        self._rank = rank
        self._nnodes = nnodes
        self._min = min_nodes or nnodes
        self._max = max_nodes or nnodes
        self._ttl = ttl
        self._interval = interval
        self._on_change = on_change
        self._stop = threading.Event()
        # rank -> (last counter value seen, local monotonic time it changed)
        self._seen: Dict[int, tuple] = {}
        self._threads: List[threading.Thread] = []
        self.enabled = True

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Register + start the heartbeat and watch threads (reference
        manager.py heartbeat thread :254)."""
        self._beat()
        t1 = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t2 = threading.Thread(target=self._watch_loop, daemon=True)
        t1.start()
        t2.start()
        self._threads = [t1, t2]

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self._interval * 2)

    exit = stop

    # -- heartbeat -------------------------------------------------------
    def _beat(self):
        self._store.add(f"elastic/beat/{self._rank}", 1)

    def _heartbeat_loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except Exception:
                pass  # transient store outage: next beat retries

    # -- watch -----------------------------------------------------------
    def alive_nodes(self) -> List[int]:
        now = time.monotonic()
        alive = []
        for r in range(self._max):
            key = f"elastic/beat/{r}"
            try:
                if not self._store.check(key):
                    continue
                # add(key, 0) reads the counter without bumping it
                ctr = self._store.add(key, 0)
            except Exception:
                continue
            last = self._seen.get(r)
            if last is None or last[0] != ctr:
                self._seen[r] = (ctr, now)
                alive.append(r)
            elif now - last[1] <= self._ttl:
                alive.append(r)
        return alive

    def _watch_loop(self):
        prev = set()
        while not self._stop.wait(self._interval):
            try:
                cur = set(self.alive_nodes())
            except Exception:
                continue
            if prev and cur != prev and self._on_change is not None:
                self._on_change(sorted(cur))
            prev = cur

    # -- reference-API surface ------------------------------------------
    def health(self) -> str:
        n = len(self.alive_nodes())
        if n >= self._nnodes:
            return ElasticStatus.COMPLETED
        if n >= self._min:
            return ElasticStatus.RESTART  # shrink within [min, max]
        return ElasticStatus.HOLD  # wait for nodes to come back

    def wait(self, timeout: float = 300.0) -> bool:
        """Block until at least min nodes are alive (rescaled bring-up)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.alive_nodes()) >= self._min:
                return True
            time.sleep(self._interval)
        return False
