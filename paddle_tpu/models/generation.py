"""Autoregressive decode engine: KV cache + retrace-free generate().

Reference analog: PaddleNLP's ``GenerationMixin`` (greedy/sampling search
over a decoder with cache) and the reference's fused_multi_transformer
decode path.  TPU-native redesign:

- **Static shapes everywhere.**  The KV cache is preallocated at
  ``[B, H, max_seq, D]`` (bf16 by default) and written position-by-
  position with ``jax.lax.dynamic_update_slice``; the *position* is a
  traced scalar, never a shape.  One prefill program (keyed on the prompt
  shape) and ONE decode program serve the whole generation loop — after
  warmup there are **zero retraces** no matter how many tokens are
  generated.
- **Donated cache.**  Both steps run through ``jit.to_static``, whose
  scout classifies the cache tensors (and the RNG key under sampling) as
  mutated captured state and donates them to XLA — each decode step
  aliases the cache update into the same HBM buffers, so generation
  holds ONE cache copy regardless of length (flat
  ``paddle_tpu.core.memory`` peak across steps).
- **q-len-1 attention kernel.**  Decode attention routes to the Pallas
  flash-decode kernel (``ops/pallas_kernels/decode_attention.py``) on
  TPU-eligible shapes, with the jnp-composed expression as fallback.
- Sampling (greedy / temperature / top-k / top-p) composes from
  ``ops/search`` + ``ops/random`` at Tensor level, so it traces into the
  same compiled step; temperature and top-p ride as traced scalars (one
  compiled program serves every setting), while top-k is static.

Model contract: a model mixes in :class:`GenerationMixin` and implements
``new_kv_cache(batch_size, max_seq, dtype)`` plus
``_cached_lm_logits(input_ids, kv_cache, cache_index) -> [B, S, V]``
(which must write the step's K/V into the cache in place).
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..core.dtype import to_jax_dtype
from ..nn import functional as F
from ..ops import dispatch
from ..tensor import Tensor, to_tensor

__all__ = [
    "KVCache",
    "GenerationMixin",
    "filter_logits",
    "sample_tokens",
    "generate",
    "trace_counts",
    "reset_trace_counts",
]


class _KVBuffers:
    """Shared buffer bookkeeping for KV caches exposing ``k``/``v`` (+
    ``stacked``): size accounting and eager release.  Used by both the
    contiguous :class:`KVCache` and the serving page pool
    (``serving.paged_cache.PagedKVCache``) so release semantics cannot
    drift between them."""

    def _tensors(self) -> List[Tensor]:
        return ([self.k, self.v] if self.stacked
                else list(self.k) + list(self.v))

    @property
    def nbytes(self) -> int:
        return sum(int(np.prod(t._value.shape)) * t._value.dtype.itemsize
                   for t in self._tensors())

    def release(self):
        """Delete the cache's device buffers NOW.  Dropping the python
        refs leaves HBM release to GC timing — and compiled step closures
        keep the Tensors alive anyway; jax's ``Array.delete()`` frees the
        buffers eagerly.  The cache is unusable afterwards."""
        for t in self._tensors():
            v = t._value
            delete = getattr(v, "delete", None)
            if delete is not None:
                try:
                    delete()
                except Exception:  # noqa: BLE001 — already deleted/donated
                    pass


class KVCache(_KVBuffers):
    """Preallocated static-shape KV cache.

    ``stacked=False``: per-layer Tensor pairs ``k[i]/v[i]`` of shape
    ``[B, H, max_seq, D]`` (the layered ``GPTModel`` path).
    ``stacked=True``: single Tensor pair of shape ``[L, B, H, max_seq, D]``
    scanned alongside the stacked decoder parameters.

    The tensors are plain framework Tensors so in-place updates
    (``_set_value``) are mutation-logged — ``jit.to_static`` donates them
    and the compiled decode step aliases the update into the same HBM.
    Stale content past the current length is never read (every read is
    length-masked), so a cache can be reused across generate() calls
    without re-zeroing.
    """

    def __init__(self, num_layers: int, batch_size: int, num_heads: int,
                 max_seq: int, head_dim: int, dtype: str = "bfloat16",
                 stacked: bool = False):
        jd = to_jax_dtype(dtype)
        self.num_layers = num_layers
        self.batch_size = batch_size
        self.num_heads = num_heads
        self.max_seq = max_seq
        self.head_dim = head_dim
        self.dtype = str(dtype)
        self.stacked = stacked
        if stacked:
            shape = (num_layers, batch_size, num_heads, max_seq, head_dim)
            self.k = Tensor(jnp.zeros(shape, jd))
            self.v = Tensor(jnp.zeros(shape, jd))
        else:
            shape = (batch_size, num_heads, max_seq, head_dim)
            self.k = [Tensor(jnp.zeros(shape, jd)) for _ in range(num_layers)]
            self.v = [Tensor(jnp.zeros(shape, jd)) for _ in range(num_layers)]

    def layer(self, i: int):
        """(k, v) Tensors for layer ``i`` (layered layout only)."""
        if self.stacked:
            raise ValueError("layer() is for the per-layer cache layout; "
                             "the stacked cache is scanned whole")
        return self.k[i], self.v[i]


# ---------------------------------------------------------------------------
# sampling (ops/search + ops/random at Tensor level — traces into the step)
# ---------------------------------------------------------------------------

_NEG = -1e30


def filter_logits(logits: Tensor, top_k: int = 0,
                  top_p: Optional[Tensor] = None) -> Tensor:
    """Top-k / nucleus (top-p) logit filtering over ``[B, V]``.

    ``top_k`` is static (changes the compiled graph); ``top_p`` is a
    traced scalar Tensor in (0, 1].  Filtered positions get -1e30 so the
    downstream softmax renormalizes over the kept set.  Top-p keeps the
    smallest prefix of the probability-sorted vocab whose mass reaches
    ``top_p`` (always at least the argmax token).
    """
    vocab = logits.shape[-1]
    if top_k and top_k > 0 and top_k < vocab:
        vals, _ = ops.topk(logits, top_k, axis=-1)
        kth = vals[:, -1:]                                   # [B, 1]
        logits = ops.where(logits < kth,
                           ops.full_like(logits, _NEG), logits)
    if top_p is not None:
        sorted_l = ops.sort(logits, axis=-1, descending=True)
        probs = F.softmax(sorted_l, axis=-1)
        # mass strictly above each rank; rank kept iff that mass < top_p
        prev_mass = ops.cumsum(probs, axis=-1) - probs
        keep = prev_mass < top_p
        thresh = ops.min(
            ops.where(keep, sorted_l, ops.full_like(sorted_l, -_NEG)),
            axis=-1, keepdim=True)
        logits = ops.where(logits < thresh,
                           ops.full_like(logits, _NEG), logits)
    return logits


def sample_tokens(logits: Tensor, *, do_sample: bool,
                  temperature: Optional[Tensor] = None, top_k: int = 0,
                  top_p: Optional[Tensor] = None) -> Tensor:
    """Next-token selection over ``[B, V]`` logits -> int64 ``[B]``.

    Greedy is a pure argmax; sampling applies temperature then top-k/
    top-p filtering and draws via the Gumbel-argmax trick with a key
    split from the global generator (the generator state functionalizes
    under jit.to_static, so compiled sampling stays reproducible)."""
    if not do_sample:
        return ops.argmax(logits, axis=-1)
    if temperature is not None:
        logits = logits / temperature
    logits = filter_logits(logits, top_k=top_k, top_p=top_p)
    from ..ops.random import default_generator

    key = default_generator.split()

    def fn(raw):
        g = jax.random.gumbel(key, raw.shape, jnp.float32)
        return jnp.argmax(raw.astype(jnp.float32) + g,
                          axis=-1).astype(jnp.int64)

    # fresh key closure every call: opt out of the eager op cache
    return dispatch.apply_nondiff(fn, logits, _cacheable=False)


# ---------------------------------------------------------------------------
# the two-program decode engine
# ---------------------------------------------------------------------------

# python-body execution counters: the step bodies run ONLY while tracing
# (abstract scout + jit trace — twice per compile), never on cached
# compiled calls.  Tests assert these stay frozen across N decode steps:
# the retrace-freedom invariant.
_TRACE_COUNTS = {"prefill": 0, "decode": 0}


def trace_counts() -> dict:
    return dict(_TRACE_COUNTS)


def reset_trace_counts():
    _TRACE_COUNTS["prefill"] = 0
    _TRACE_COUNTS["decode"] = 0


class _DecodeEngine:
    """One (prefill, decode) compiled-step pair bound to a model + cache.

    Cached on the model per (batch, max_seq, cache dtype, sampling
    topology) — repeated generate() calls reuse the compiled programs AND
    the cache HBM."""

    def __init__(self, model, cache: KVCache, *, do_sample: bool,
                 top_k: int, use_top_p: bool):
        from ..jit.api import to_static

        self.cache = cache
        self.do_sample = do_sample
        self.top_k = top_k
        self.use_top_p = use_top_p
        # one generate() at a time per engine: the compiled steps mutate
        # the SHARED cache, so concurrent callers (PredictorPool threads)
        # must serialize per engine — distinct engines run concurrently.
        # `released` flips under the lock when eviction deletes the cache
        # buffers; a caller that raced the eviction (engine looked up, lock
        # not yet taken) sees it and fetches a fresh engine instead of
        # dispatching into deleted arrays.
        self.lock = threading.RLock()
        self.released = False

        def prefill_step(ids, temperature, top_p):
            _TRACE_COUNTS["prefill"] += 1
            with dispatch.no_grad():
                logits = model._cached_lm_logits(ids, cache, 0)
                last = logits[:, -1, :].astype("float32")      # [B, V]
                tok = sample_tokens(
                    last, do_sample=do_sample,
                    temperature=temperature if do_sample else None,
                    top_k=top_k, top_p=top_p if use_top_p else None)
            return tok, last

        def decode_step(tok, pos, temperature, top_p):
            _TRACE_COUNTS["decode"] += 1
            with dispatch.no_grad():
                ids = ops.reshape(tok, [-1, 1])                # [B, 1]
                logits = model._cached_lm_logits(ids, cache, pos)
                last = logits[:, -1, :].astype("float32")
                nxt = sample_tokens(
                    last, do_sample=do_sample,
                    temperature=temperature if do_sample else None,
                    top_k=top_k, top_p=top_p if use_top_p else None)
            return nxt, pos + 1, last

        self.prefill = to_static(prefill_step)
        self.decode = to_static(decode_step)

    @property
    def compiled_programs(self) -> int:
        """Distinct compiled programs behind this engine (prefill entries
        are per prompt shape; decode is always exactly one)."""
        return len(self.prefill.code_cache) + len(self.decode.code_cache)

    def lint_reports(self):
        """Graph-lint reports of every compiled prefill/decode program
        (populated when FLAGS_graph_lint / PADDLE_TPU_GRAPH_LINT=1 was on
        at compile time; see docs/graph_lint.md)."""
        return self.prefill.lint_reports() + self.decode.lint_reports()

    def release(self):
        """Free the engine's KV-cache HBM eagerly (LRU eviction /
        clear_decode_cache): the compiled step closures pin the cache
        Tensors, so without an explicit ``delete()`` the buffers wait on
        GC.  Taking ``self.lock`` first means an in-flight generate() on
        this engine finishes its loop before the buffers vanish under it
        (the evictor blocks, it does not corrupt); ``released`` tells a
        caller that looked the engine up just before the eviction to
        retry with a fresh one."""
        with self.lock:
            self.cache.release()
            self.released = True


# each cached engine pins a full KV cache in HBM; bound how many distinct
# (batch, max_seq, dtype, sampling-topology) combinations stay resident
_MAX_ENGINES = 4


def _engine_for(model, batch: int, max_seq: int, cache_dtype: str, *,
                do_sample: bool, top_k: int, use_top_p: bool) -> _DecodeEngine:
    # model.__dict__ directly: Layer.__setattr__ must not see cache Tensors
    # (they are serving state, not parameters/buffers).  dict.setdefault is
    # atomic, so concurrent first calls agree on one lock/registry.
    lock = model.__dict__.setdefault("_decode_engines_lock",
                                     threading.Lock())
    with lock:
        engines = model.__dict__.setdefault("_decode_engines", {})
        key = (batch, max_seq, str(cache_dtype), bool(do_sample), int(top_k),
               bool(use_top_p))
        eng = engines.pop(key, None)
        if eng is not None and eng.released:
            eng = None        # buffers already deleted: build a fresh one
        if eng is None:
            while len(engines) >= _MAX_ENGINES:
                # LRU: dict order is move-to-back-on-use; evicting the
                # engine deletes its cache buffers explicitly (the compiled
                # step closures would otherwise pin them until GC)
                old_key = next(iter(engines))
                engines.pop(old_key).release()
            cache = model.new_kv_cache(batch, max_seq, dtype=cache_dtype)
            eng = _DecodeEngine(model, cache, do_sample=do_sample,
                                top_k=top_k, use_top_p=use_top_p)
        engines[key] = eng  # (re)insert at the back = most recently used
        return eng


def generate(model, input_ids, max_new_tokens: int = 32, *,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None,
             max_seq_len: Optional[int] = None,
             cache_dtype: str = "bfloat16", return_logits: bool = False):
    """Autoregressive generation from ``input_ids`` ``[B, S0]`` (int64).

    Returns ``[B, S0 + max_new_tokens]`` token ids (prompt included), or
    ``(ids, logits)`` with ``logits`` ``[B, max_new_tokens, V]`` fp32 (the
    pre-sampling logits of each generated position) when
    ``return_logits=True``.

    Without ``eos_token_id`` the loop is fully asynchronous — N compiled
    step dispatches with no host sync until the result is read.  With it,
    each step syncs the token back to decide early stop; rows keep their
    first ``eos_token_id`` and are padded with it afterwards.  Note that
    under ``return_logits`` positions at/after a row's first eos carry the
    distribution conditioned on the raw sampled continuation (the id
    padding is applied afterwards, host-side); combining it with
    ``eos_token_id`` also disables the all-rows-done early stop so every
    logits row is real.
    """
    ids = to_tensor(input_ids, dtype="int64") if not isinstance(
        input_ids, Tensor) else input_ids
    b, s0 = int(ids.shape[0]), int(ids.shape[1])
    cfg = model.config
    max_seq = int(max_seq_len or cfg.max_position_embeddings)
    if max_seq > cfg.max_position_embeddings:
        raise ValueError(
            f"max_seq_len={max_seq} exceeds max_position_embeddings="
            f"{cfg.max_position_embeddings}")
    if s0 + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt ({s0}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"cache length {max_seq}; raise max_seq_len (<= "
            f"max_position_embeddings) or shorten the request")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if do_sample and not float(temperature) > 0.0:
        raise ValueError("temperature must be > 0 when do_sample=True")

    use_top_p = do_sample and top_p is not None
    temp_t = to_tensor(np.float32(temperature))
    top_p_t = to_tensor(np.float32(top_p if top_p is not None else 1.0))

    # generation is an eval-time graph: dropout must not trace in.
    # eng.lock: the compiled steps mutate the engine's shared cache, so a
    # second thread on the same request shape serializes here instead of
    # interleaving decode steps through one cache (PredictorPool threads).
    # The retry loop closes the lookup->lock window: an engine evicted in
    # between flips `released` under its lock, and we fetch a fresh one
    # instead of dispatching into deleted cache buffers.
    while True:
        eng = _engine_for(model, b, max_seq, cache_dtype,
                          do_sample=do_sample, top_k=int(top_k or 0),
                          use_top_p=use_top_p)
        with eng.lock:
            if eng.released:
                continue
            was_training = model.training
            if was_training:
                model.eval()
            try:
                tok, last = eng.prefill(ids, temp_t, top_p_t)
                toks: List[Tensor] = [tok]
                logit_steps: List[Tensor] = [last] if return_logits else []
                pos = to_tensor(np.int32(s0))
                done = None
                if eos_token_id is not None:
                    done = np.asarray(tok.numpy()) == eos_token_id
                for _ in range(max_new_tokens - 1):
                    if done is not None and bool(done.all()) \
                            and not return_logits:
                        # every row finished: pad the remaining steps
                        # host-side instead of decoding.  (With
                        # return_logits the loop keeps decoding so every
                        # returned row is a REAL model distribution —
                        # zero-padded rows would silently read as uniform
                        # to a perplexity/logprob consumer.)
                        toks.append(ops.full_like(tok, eos_token_id))
                        continue
                    tok, pos, last = eng.decode(tok, pos, temp_t, top_p_t)
                    toks.append(tok)
                    if return_logits:
                        logit_steps.append(last)
                    if done is not None:
                        done = done | (np.asarray(tok.numpy())
                                       == eos_token_id)
            finally:
                if was_training:
                    model.train()
            break

    gen = ops.stack(toks, axis=1)                               # [B, N]
    if eos_token_id is not None:
        # freeze every row at its first eos: positions after it become eos
        g = np.asarray(gen.numpy())
        hit = np.cumsum(g == eos_token_id, axis=1) > 0
        after = np.zeros_like(hit)
        after[:, 1:] = hit[:, :-1]
        g = np.where(after, eos_token_id, g)
        gen = to_tensor(g, dtype="int64")
    out = ops.concat([ids, gen], axis=1)
    if return_logits:
        return out, ops.stack(logit_steps, axis=1)              # [B, N, V]
    return out


class GenerationMixin:
    """Adds ``generate()`` to a causal LM exposing the cache contract
    (``new_kv_cache`` + ``_cached_lm_logits``).

    Engines (compiled prefill/decode pair + their KV-cache HBM) are cached
    per request shape, LRU-bounded at ``_MAX_ENGINES``; call
    :meth:`clear_decode_cache` to release them all eagerly (e.g. before
    resuming training on a memory-tight chip)."""

    def generate(self, input_ids, max_new_tokens: int = 32, **kwargs):
        return generate(self, input_ids, max_new_tokens, **kwargs)

    def clear_decode_cache(self):
        """Drop every cached decode engine AND delete its KV-cache device
        buffers eagerly (the compiled step closures would otherwise pin
        the HBM until GC collects the whole engine graph)."""
        lock = self.__dict__.get("_decode_engines_lock")
        engines = (self.__dict__.pop("_decode_engines", None)
                   if lock is None else None)
        if lock is not None:
            with lock:
                engines = self.__dict__.pop("_decode_engines", None)
        for eng in (engines or {}).values():
            eng.release()
