"""ONNX export surface (reference: python/paddle/onnx/export.py, which
delegates to the external paddle2onnx package).

This build has no onnx/paddle2onnx (zero-egress image); the portable
serialized form of a compiled model is the StableHLO program written by
``paddle_tpu.jit.save`` (load it anywhere with jax.export, including
non-TPU backends).  ``export`` therefore writes that artifact and raises
a clear error only if asked for a literal .onnx protobuf.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference onnx/export.py export(layer, path, input_spec).

    Writes the StableHLO inference artifact at ``path`` (pdmodel/pdiparams
    pair).  A true ONNX protobuf requires the external paddle2onnx/onnx
    packages, which are not in this image.
    """
    if str(path).endswith(".onnx"):
        raise NotImplementedError(
            "literal .onnx protobuf export needs the external onnx package "
            "(not in this zero-egress image); jit.save's StableHLO artifact "
            "is the portable compiled-model format here")
    from ..jit.save_load import save as _save

    _save(layer, path, input_spec=input_spec)
    return path
