#!/usr/bin/env python
"""Telemetry CI gate (run_tests.sh gate #6; PADDLE_TPU_SKIP_OBS_GATE=1
skips).  Three checks, all CPU-fast:

1. **Disabled-path overhead** — telemetry off must be near-free.  The
   instrumented hot paths guard on ONE module-global read
   (``trace._tracer is None``), so the gate measures (a) the cost of a
   disabled ``span()`` call and (b) the cost of one compiled
   ``to_static`` dispatch on the dispatch-micro-bench shapes, then
   asserts a full serving step's worth of disabled call-sites costs
   <3% of one dispatch.  An enabled-vs-disabled A/B of the same
   dispatch loop is printed for reference (the <5% serving tokens/sec
   bound is benched separately via serving_bench --chaos / ISSUE 9).

2. **Trace validity** — a tiny serving run with tracing enabled must
   export Chrome-trace JSON that (a) parses, (b) contains the serving
   phase spans, and (c) nests plan/pack/dispatch/harvest/commit inside
   their ``serve.step`` on the same thread row — the structure
   chrome://tracing / Perfetto renders.

3. **Prometheus exposition** — ``registry().prometheus_text()`` must
   parse line-by-line (HELP/TYPE comments + ``name{labels} value``
   samples), histogram bucket counts must be monotone in ``le`` with
   the ``+Inf`` bucket equal to ``_count``, and the serving SLO
   histograms must be present after the serving run.

Exit codes: 0 ok, 1 any check failed.
"""
from __future__ import annotations

import json
import math
import os
import re
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np  # noqa: E402

#: span call-sites one serving step passes through (engine.step():
#: serve.step/plan/pack/dispatch/device_step/harvest/commit) — reported
#: so the step-level cost is visible next to the per-site gate
_STEP_SPAN_SITES = 7

#: the budget from docs/observability.md: ONE disabled telemetry
#: call-site must cost under 3% of one compiled dispatch (the finest
#: instrumented unit; a serving step is ~30x a dispatch and carries
#: only _STEP_SPAN_SITES sites)
_DISABLED_BUDGET = 0.03


def check_overhead() -> dict:
    import paddle_tpu as pt
    from paddle_tpu.jit.api import to_static
    from paddle_tpu.telemetry import trace

    # the gate measures both arms itself — detach a PADDLE_TPU_TRACE=1
    # import-time tracer rather than failing the developer's environment
    if trace.active() is not None:
        print("obs_gate: note: detaching the ambient tracer "
              "(PADDLE_TPU_TRACE=1?) for the overhead A/B")
        trace.disable()

    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(64, 64).astype(np.float32))
    w = pt.to_tensor(rng.randn(64, 64).astype(np.float32))
    b = pt.to_tensor(rng.randn(64).astype(np.float32))

    fn = to_static(lambda x, w, b: pt.add(pt.matmul(x, w), b))

    def dispatch_loop(iters):
        out = None
        for _ in range(iters):
            out = fn(x, w, b)
        out._value.block_until_ready()

    # -- per-call cost of the disabled span() no-op -----------------------
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("obs_gate.noop"):
            pass
    span_ns = (time.perf_counter() - t0) / n * 1e9

    # -- per-call cost of one compiled dispatch, telemetry OFF vs ON.
    # Interleaved rounds + min-of-rounds per arm: this host's load is
    # spiky enough that two sequential 2000-iter loops can differ 2x on
    # noise alone; alternating short rounds and taking each arm's best
    # round measures the machinery, not the neighbors. -------------------
    dispatch_loop(200)                       # warmup: compile + caches
    rounds, iters = 5, 500
    off_best = on_best = math.inf
    tr = None
    try:
        for _ in range(rounds):
            t0 = time.perf_counter()
            dispatch_loop(iters)
            off_best = min(off_best, (time.perf_counter() - t0) / iters)
            tr = trace.enable(capacity=4 * iters)
            t0 = time.perf_counter()
            dispatch_loop(iters)
            on_best = min(on_best, (time.perf_counter() - t0) / iters)
            trace.disable()
        assert tr is not None and len(tr) > 0, \
            "enabled tracer recorded no dispatch spans"
    finally:
        trace.disable()
    off_us, on_us = off_best * 1e6, on_best * 1e6

    frac = span_ns / 1e3 / off_us
    res = {
        "span_disabled_ns": round(span_ns, 1),
        "dispatch_off_us": round(off_us, 2),
        "dispatch_on_us": round(on_us, 2),
        "enabled_overhead_pct": round((on_us / off_us - 1.0) * 100.0, 2),
        "disabled_site_cost_pct": round(frac * 100.0, 3),
        "disabled_step_cost_us": round(_STEP_SPAN_SITES * span_ns / 1e3, 2),
    }
    assert frac < _DISABLED_BUDGET, (
        f"disabled telemetry too expensive: one span site costs "
        f"{span_ns:.0f}ns = {frac * 100:.2f}% of one {off_us:.1f}us "
        f"dispatch (budget {_DISABLED_BUDGET * 100:.0f}%)")
    return res


def _run_traced_engine():
    """One tiny serving run with tracing enabled; returns (tracer,
    engine metrics, prometheus exposition) — the exposition is captured
    BEFORE close(), which drops the engine's series from the registry."""
    import paddle_tpu as pt
    from paddle_tpu.models import GPTForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.telemetry import metrics, trace

    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    rng = np.random.RandomState(3)
    tr = trace.enable()
    try:
        eng = ServingEngine(m, num_slots=2, page_size=16, max_context=64,
                            cache_dtype="float32")
        for s in (5, 11, 8):
            eng.submit(rng.randint(0, cfg.vocab_size, (s,)), 4)
        eng.run_until_idle(max_steps=500)
        mets = eng.metrics()
        text = metrics.registry().prometheus_text()
        eng.close()
    finally:
        trace.disable()
    return tr, mets, text


_PHASES = ("serve.plan", "serve.pack", "serve.dispatch", "serve.harvest",
           "serve.commit")


def check_trace(tr) -> dict:
    from paddle_tpu.telemetry import trace

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "trace.json")
        trace.export_chrome_trace(path, tracer=tr)
        with open(path) as f:
            doc = json.load(f)

    events = doc["traceEvents"]
    assert isinstance(events, list) and events, "empty traceEvents"
    comp = [e for e in events if e.get("ph") == "X"]
    meta = [e for e in events if e.get("ph") == "M"]
    for e in comp:
        for k in ("name", "pid", "tid", "ts", "dur"):
            assert k in e, f"complete event missing {k!r}: {e}"
    assert meta, "no thread_name metadata events"

    names = {e["name"] for e in comp}
    missing = {"serve.step", *_PHASES} - names
    assert not missing, f"serving-phase spans missing from trace: {missing}"

    # nesting: every phase span must sit inside a serve.step interval on
    # the SAME thread row (0.5us slack for ns->us float rounding)
    steps = [e for e in comp if e["name"] == "serve.step"]
    eps = 0.5
    for e in (e for e in comp if e["name"] in _PHASES):
        ok = any(s["tid"] == e["tid"]
                 and s["ts"] - eps <= e["ts"]
                 and e["ts"] + e["dur"] <= s["ts"] + s["dur"] + eps
                 for s in steps)
        assert ok, f"{e['name']} span not nested in any serve.step: {e}"
    return {"events": len(events), "complete": len(comp),
            "span_names": len(names), "steps": len(steps)}


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?(?:[0-9.]+(?:e[+-]?[0-9]+)?|inf|nan))$",
    re.IGNORECASE)


def check_prometheus(text: str) -> dict:
    lines = [ln for ln in text.splitlines() if ln]
    samples = 0
    hist_series: dict = {}
    counts: dict = {}
    for ln in lines:
        if ln.startswith("# HELP ") or ln.startswith("# TYPE "):
            continue
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable exposition line: {ln!r}"
        samples += 1
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        if name.endswith("_bucket"):
            le = re.search(r'le="([^"]*)"', labels)
            assert le, f"bucket line without le: {ln!r}"
            rest = re.sub(r',?le="[^"]*"', "", labels)
            if rest == "{}":
                rest = ""
            key = (name[:-len("_bucket")], rest)
            bound = float("inf") if le.group(1) == "+Inf" else float(le.group(1))
            hist_series.setdefault(key, []).append((bound, float(value)))
        elif name.endswith("_count"):
            counts[(name[:-len("_count")], labels)] = float(value)

    assert samples, "empty Prometheus exposition"
    assert hist_series, "no histogram series in exposition"
    for (hname, labels), series in hist_series.items():
        series.sort(key=lambda bv: bv[0])
        cum = [v for _, v in series]
        assert cum == sorted(cum), \
            f"{hname}{labels}: bucket counts not monotone in le: {cum}"
        assert series[-1][0] == float("inf"), f"{hname}{labels}: no +Inf bucket"
        total = counts.get((hname, labels))
        assert total == series[-1][1], (
            f"{hname}{labels}: +Inf bucket {series[-1][1]} != _count {total}")

    hist_names = {h for h, _ in hist_series}
    for required in ("serving_ttft_seconds", "serving_e2e_seconds"):
        assert required in hist_names, \
            f"serving SLO histogram {required} missing from exposition"
    return {"lines": len(lines), "samples": samples,
            "histogram_series": len(hist_series)}


def main() -> int:
    checks = []

    def run(name, fn, *a):
        try:
            res = fn(*a)
            print(f"obs_gate: {name}: OK {json.dumps(res)}")
            return res
        except AssertionError as e:
            print(f"obs_gate: {name}: FAIL {e}")
            checks.append(name)
            return None

    run("overhead", check_overhead)
    out = text = None
    try:
        tr, mets, text = _run_traced_engine()
        slo = mets.get("slo", {})
        if not slo.get("ttft", {}).get("count"):
            print("obs_gate: engine: FAIL TTFT histogram empty after run")
            checks.append("engine")
        out = tr
    except Exception as e:  # noqa: BLE001 — report and continue
        print(f"obs_gate: engine: FAIL {type(e).__name__}: {e}")
        checks.append("engine")
    if out is not None:
        run("chrome_trace", check_trace, out)
    if text is not None:
        run("prometheus", check_prometheus, text)

    if checks:
        print(f"obs_gate: FAILED: {checks}")
        return 1
    print("obs_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
