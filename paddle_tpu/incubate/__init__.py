"""incubate: experimental features (reference: python/paddle/incubate/)."""
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .selected_rows import SelectedRows, merge_selected_rows  # noqa: F401
from .string_tensor import (  # noqa: F401
    StringTensor, strings_empty, strings_lower, strings_upper)
