"""Disaggregated serving: dedicated prefill and decode replica roles
with page-granular KV hand-off.

The second half of ROADMAP item 2 (the first — the global prefix cache —
shipped as PR 16): long prompts and steady decode streams want opposite
step shapes.  A colocated replica's fused step mixes both, so one long
prefill run dilutes the grid/q-row occupancy of every seated decoder in
the SAME dispatch — their next token cannot arrive before the prompt
finishes.  Disaggregation splits the dp replicas into roles:

- **PREFILL** replicas admit prompts (prefix-locality routed, reusing
  ``PrefixCache.acquire`` so only the uncached tail prefills), run
  prefill-heavy steps at a large token budget, and sample each request's
  FIRST token (TTFT is paid here);
- **DECODE** replicas never admit — requests ARRIVE via
  :class:`PageTransfer` with their KV pages already filled, and every
  step is decode-only (tiny ``prefill_token_budget=1`` geometry, so the
  compiled program is small and its occupancy undiluted).  Decode
  replicas may run several sub-steps per cluster tick
  (``decode_steps_per_tick``) — their dispatches are cheap and no longer
  gated on any prefill finishing, which is exactly the ITL win
  serving_bench's ``--disagg`` sweep measures;
- **COLOCATED** replicas behave as before (both phases; an
  all-colocated role vector makes :class:`DisaggServingEngine` a plain
  :class:`~.sharded.ShardedServingEngine`).

**The hand-off.**  The ragged fused step reads KV through per-slot page
tables only (PR 8), so moving a request is moving PAGES: at the start of
every cluster tick the engine scans prefill replicas for seated requests
whose prompt completed (``RequestState.DECODE``) and hands each to a
decode replica chosen by load / LoRA residency / speculative acceptance.
The copy is a device-to-device gather/scatter batched per transfer (one
fused indexed read + ``.at[...].set`` write per pool tensor, int8 scale
sidecars included), host-staged on CPU.

**Ownership protocol** (mirrored in both ``BlockAllocator`` ledgers so
free+used+spec+shared == capacity holds on BOTH pools at every step
boundary, mid-transfer faults included):

1. destination reserves the request's FULL page grant into its spec
   ledger (``reserve_spec`` — the same rollback-exact discipline PR 15
   proved on speculative reservations) BEFORE any copy;
2. the filled pages copy (a fault here — ``transfer_stall`` /
   ``transfer_error`` / ``transfer_partial`` at the ``page_transfer``
   hook point — aborts the transfer: the destination reservation rolls
   back via ``rollback_spec`` and the source, still seated, simply keeps
   decoding and re-routes next tick);
3. the copy commits atomically at harvest (``commit_spec`` — spec →
   allocated) and the destination seats the request
   (``ServingEngine.adopt_transferred``: slot at the source's position,
   last sampled token in the step-input mirror — the next decode step is
   bit-identical to the one the source would have run, which is what
   keeps greedy output BITWISE equal to a colocated run);
4. only after commit does the source release
   (``ServingEngine.release_transferred``: pages, prefix-cache reader
   references and LoRA references drop — no terminal transition, the
   request lives on).  If the destination dies instead, the source never
   released: it retains ownership and re-routes.

**Elasticity.**  :class:`DisaggElasticController` runs one PR-19
controller per role pool over restricted views of the same cluster: the
prefill pool regulates TTFT (and owns the brownout ladder), the decode
pool regulates ITL with ``brownout_enabled=False`` (two controllers must
not duel over the shared cluster-wide rungs) — so the two pools scale
independently from their own SLO signals while drain/re-home and the
ladder compose unchanged.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..analysis.cost_model import page_transfer_bytes
from ..telemetry import metrics as _tmetrics
from .elastic import ElasticConfig, ElasticServingController
from .engine import Request, RequestState, ServingEngine
from .paged_cache import pages_for_tokens
from .placement import (
    PrefixLocalityPlacement,
    replica_load,
    replica_role,
    replica_signals,
)
from .sharded import ShardedServingEngine

__all__ = [
    "ROLE_PREFILL", "ROLE_DECODE", "ROLE_COLOCATED", "ROLES",
    "RolePlacement", "PageTransfer", "PageTransferAborted",
    "DisaggServingEngine", "DisaggElasticController",
]

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_COLOCATED = "colocated"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_COLOCATED)


class PageTransferAborted(RuntimeError):
    """A hand-off that did not commit: the destination reservation was
    rolled back and the source retains ownership (the request keeps
    decoding where it is and may re-route next tick)."""


class RolePlacement(PrefixLocalityPlacement):
    """Role-aware admission routing: fresh submissions — and re-homed
    checkpoints, which need re-prefilling — go to prefill/colocated
    replicas, ranked prefix-locality first among them (siblings of a
    prompt family keep hitting the same warm cache).  Decode replicas
    are ranked LAST rather than excluded: if every admitting replica is
    dead or draining, a decode replica re-prefilling (degraded but
    correct — its budget-1 geometry still makes progress) beats shedding
    the request."""

    def rank_for(self, engines: Sequence, prompt,
                 adapter: Optional[str] = None) -> List[int]:
        order = super().rank_for(engines, prompt, adapter=adapter)
        admitting = [i for i in order
                     if replica_role(engines[i]) != ROLE_DECODE]
        return admitting + [i for i in order if i not in admitting]


# ---------------------------------------------------------------------------
# the hand-off
# ---------------------------------------------------------------------------

class PageTransfer:
    """Moves one request's filled pool pages between two replicas'
    pools, ownership-exact (module docstring, "Ownership protocol").

    The copy itself is ONE batched gather/scatter per pool tensor:
    ``dst.at[dst_pages].set(src[src_pages])`` — eager indexed ops on the
    captured pool Tensors (the in-place ``_set_value`` idiom the LoRA
    slabs proved: pool writes never retrace the fused step, so trace
    counts stay <=2 per role).  On devices that cannot express the
    cross-pool read in one expression — notably the CPU test platform's
    single-buffer pools — the gather stages through host numpy
    (bit-exact round trip) and only the scatter runs on device."""

    def __init__(self, fault_hook: Optional[Callable] = None):
        self._fault_hook = fault_hook

    # -- copy mechanics ----------------------------------------------------
    @staticmethod
    def _pairs(src_cache, dst_cache):
        """(src Tensor, dst Tensor, pages axis) for every pool buffer the
        transfer must move — K/V per layer (or the stacked pair) plus the
        int8 scale sidecars (a dequantizable page is page bytes AND its
        scales)."""
        if src_cache.stacked:
            pairs = [(src_cache.k, dst_cache.k, 1),
                     (src_cache.v, dst_cache.v, 1)]
            if src_cache.quantized:
                pairs += [(src_cache.k_scale, dst_cache.k_scale, 1),
                          (src_cache.v_scale, dst_cache.v_scale, 1)]
            return pairs
        pairs = [(s, d, 0) for s, d in zip(src_cache.k, dst_cache.k)]
        pairs += [(s, d, 0) for s, d in zip(src_cache.v, dst_cache.v)]
        if src_cache.quantized:
            pairs += [(s, d, 0)
                      for s, d in zip(src_cache.k_scale, dst_cache.k_scale)]
            pairs += [(s, d, 0)
                      for s, d in zip(src_cache.v_scale, dst_cache.v_scale)]
        return pairs

    @staticmethod
    def _device_to_device(src_val):
        try:
            return all(d.platform != "cpu" for d in src_val.devices())
        except Exception:  # noqa: BLE001 — fall back to host staging
            return False

    def copy_pages(self, src_cache, dst_cache,
                   src_pages: Sequence[int], dst_pages: Sequence[int]):
        """Copy ``src_pages`` of ``src_cache`` onto ``dst_pages`` of
        ``dst_cache`` (equal counts), batched per pool tensor."""
        if len(src_pages) != len(dst_pages):
            raise ValueError(f"page count mismatch: {len(src_pages)} "
                             f"!= {len(dst_pages)}")
        if not src_pages:
            return
        # pad the index arrays up to a power-of-two bucket so distinct
        # copy shapes (each pays a one-time dispatch compile) stay
        # O(log pool_pages) under batched multi-request hand-offs; the
        # padding repeats the last pair, an idempotent duplicate write
        n = len(src_pages)
        bucket = 1
        while bucket < n:
            bucket *= 2
        s_idx = np.asarray(src_pages, np.int32)
        d_idx = np.asarray(dst_pages, np.int32)
        if bucket > n:
            s_idx = np.concatenate(
                [s_idx, np.full(bucket - n, s_idx[-1], np.int32)])
            d_idx = np.concatenate(
                [d_idx, np.full(bucket - n, d_idx[-1], np.int32)])
        s_idx = jnp.asarray(s_idx)
        d_idx = jnp.asarray(d_idx)
        for s_t, d_t, axis in self._pairs(src_cache, dst_cache):
            src_val = s_t._value
            block = (src_val[:, s_idx] if axis == 1 else src_val[s_idx])
            if not self._device_to_device(src_val):
                # host-staged fallback (CPU, or pools whose meshes the
                # backend cannot bridge in one expression): numpy round
                # trip is bit-exact for every pool dtype incl. bf16/int8
                block = jnp.asarray(np.asarray(block), src_val.dtype)
            if axis == 1:
                d_t._set_value(d_t._value.at[:, d_idx].set(block))
            else:
                d_t._set_value(d_t._value.at[d_idx].set(block))

    # -- the protocol ------------------------------------------------------
    def transfer(self, src: ServingEngine, src_idx: int,
                 dst: ServingEngine, *, src_replica: int = -1,
                 dst_replica: int = -1) -> Tuple[bool, int]:
        """Attempt the full hand-off of the request seated in ``src``
        slot ``src_idx`` onto ``dst``.  Returns ``(committed, pages)``:
        ``(True, filled_pages_copied)`` when the request now lives on
        ``dst`` and the source released, ``(False, 0)`` when nothing
        moved — either a precondition failed (no destination slot/pages)
        or a mid-transfer fault aborted, in which case the destination
        reservation was rolled back and the source still owns the
        request.  Both pools' 4-term invariant holds on EVERY return."""
        slot = src.scheduler.slots[src_idx]
        if slot is None:
            return False, 0
        req = slot.request
        if not req.tokens:
            return False, 0           # no sampled token to carry yet
        n_pages = len(slot.pages)
        filled = pages_for_tokens(slot.pos, src.page_size)
        if dst._draining or not dst.scheduler.free_slot_indices():
            return False, 0
        # 1. destination reservation BEFORE any copy (spec ledger)
        d_pages = dst.allocator.reserve_spec(n_pages)
        if d_pages is None:
            return False, 0           # destination pool backpressure
        try:
            ctx = {"src": src_replica, "dst": dst_replica,
                   "request": req.id, "pages": filled, "partial": False}
            if self._fault_hook is not None:
                self._fault_hook("page_transfer", ctx)
            # 2. the copy (filled pages only — the tail of the grant has
            # never been written; its destination pages stay reserved so
            # the no-mid-decode-OOM admission guarantee carries over)
            if ctx["partial"]:
                # injected partial landing: some pages copy, then the
                # link "dies" — must be indistinguishable from a failure
                self.copy_pages(src.cache, dst.cache,
                                slot.pages[:filled // 2],
                                d_pages[:filled // 2])
                raise PageTransferAborted(
                    f"partial transfer of request {req.id}: "
                    f"{filled // 2}/{filled} pages landed")
            self.copy_pages(src.cache, dst.cache,
                            slot.pages[:filled], d_pages[:filled])
        except BaseException:
            # source dies / destination dies / injected fault: the
            # destination reservation rolls back (its half-written pages
            # return to free — every future owner fully rewrites before
            # reading) and the source, never touched, retains ownership
            dst.allocator.rollback_spec(d_pages)
            raise
        # 3. commit atomically at harvest: spec -> allocated on dst...
        dst.allocator.commit_spec(d_pages)
        idx = dst.adopt_transferred(req, d_pages, slot.pos,
                                    int(req.tokens[-1]))
        if idx is None:
            # destination refused the seat after all (drain raced in):
            # undo the commit — pages go straight back to free — and the
            # source keeps the request
            dst.allocator.free(d_pages)
            return False, 0
        # 4. ...and ONLY then does the source release its ownership
        src.release_transferred(src_idx)
        req.replica = dst_replica if dst_replica >= 0 else req.replica
        return True, filled

    def transfer_many(self, src: ServingEngine, src_idxs: Sequence[int],
                      dst: ServingEngine, *, src_replica: int = -1,
                      dst_replica: int = -1) -> Tuple[int, int, int]:
        """Batched hand-off of several requests from ``src`` to ``dst``.
        The ownership protocol stays PER REQUEST — each request gets its
        own destination reservation and fault-hook firing, and a faulted
        request rolls back alone while the rest of the batch proceeds —
        but every surviving request's pages land in ONE fused
        gather/scatter per pool tensor, so a hand-off tick pays the copy
        dispatch overhead once, not per request.  That batching is what
        keeps the hand-off gap out of the transferred requests' ITL tail
        (``serving_bench --disagg``).  Returns
        ``(committed, pages_copied, failed)``; both pools' 4-term
        invariant holds on every return."""
        staged = []           # (src_idx, slot, req, d_pages, filled)
        failed = 0
        for src_idx in src_idxs:
            slot = src.scheduler.slots[src_idx]
            if slot is None or not slot.request.tokens:
                continue
            if dst._draining or \
                    len(dst.scheduler.free_slot_indices()) <= len(staged):
                break
            req = slot.request
            filled = pages_for_tokens(slot.pos, src.page_size)
            # 1. per-request destination reservation BEFORE any copy
            d_pages = dst.allocator.reserve_spec(len(slot.pages))
            if d_pages is None:
                break         # destination pool backpressure
            try:
                ctx = {"src": src_replica, "dst": dst_replica,
                       "request": req.id, "pages": filled, "partial": False}
                if self._fault_hook is not None:
                    self._fault_hook("page_transfer", ctx)
                if ctx["partial"]:
                    self.copy_pages(src.cache, dst.cache,
                                    slot.pages[:filled // 2],
                                    d_pages[:filled // 2])
                    raise PageTransferAborted(
                        f"partial transfer of request {req.id}: "
                        f"{filled // 2}/{filled} pages landed")
            except BaseException:
                # this request's fault is its own: roll back ITS
                # reservation, keep it on the source, continue the batch
                dst.allocator.rollback_spec(d_pages)
                failed += 1
                continue
            staged.append((src_idx, slot, req, d_pages, filled))
        if not staged:
            return 0, 0, failed
        # 2. ONE copy for the whole batch (filled pages only)
        s_all: List[int] = []
        d_all: List[int] = []
        for _, slot, _, d_pages, filled in staged:
            s_all.extend(slot.pages[:filled])
            d_all.extend(d_pages[:filled])
        try:
            self.copy_pages(src.cache, dst.cache, s_all, d_all)
        except BaseException:
            # a real copy failure takes down the whole batch: every
            # reservation rolls back, the source retains every request
            for _, _, _, d_pages, _ in staged:
                dst.allocator.rollback_spec(d_pages)
            raise
        # 3+4. per-request commit / adopt / release, exactly as single
        committed = pages = 0
        for src_idx, slot, req, d_pages, filled in staged:
            dst.allocator.commit_spec(d_pages)
            idx = dst.adopt_transferred(req, d_pages, slot.pos,
                                        int(req.tokens[-1]))
            if idx is None:
                dst.allocator.free(d_pages)
                continue
            src.release_transferred(src_idx)
            req.replica = dst_replica if dst_replica >= 0 else req.replica
            committed += 1
            pages += filled
        return committed, pages, failed


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class DisaggServingEngine(ShardedServingEngine):
    """A :class:`~.sharded.ShardedServingEngine` whose dp replicas carry
    roles (module docstring).  ``roles`` fixes both dp (its length) and
    each replica's job; ``prefill_kw`` / ``decode_kw`` overlay
    role-specific engine knobs on top of the shared ``engine_kw``
    (prefill replicas usually get a large ``prefill_token_budget``;
    decode replicas default to the minimal budget-1 geometry).  Every
    replica engine is constructed with its ``role`` — the per-role
    ``role`` label on the SLO histograms and the role-aware placement
    both key on it."""

    def __init__(self, model, *, roles: Sequence[str] = (ROLE_PREFILL,
                                                         ROLE_DECODE),
                 mp: int = 1, devices=None, model_factory=None,
                 placement=None, engine_factory=None,
                 prefill_kw: Optional[dict] = None,
                 decode_kw: Optional[dict] = None,
                 decode_steps_per_tick: int = 1,
                 **engine_kw):
        roles = tuple(str(r) for r in roles)
        for r in roles:
            if r not in ROLES:
                raise ValueError(f"unknown replica role {r!r}; "
                                 f"expected one of {ROLES}")
        if not roles:
            raise ValueError("roles must name at least one replica")
        if all(r == ROLE_DECODE for r in roles):
            raise ValueError(
                "every replica is decode-role: nothing can admit — at "
                "least one prefill or colocated replica is required")
        self.roles = roles
        self.decode_steps_per_tick = max(int(decode_steps_per_tick), 1)
        p_kw = dict(prefill_kw or {})
        d_kw = dict(decode_kw or {})
        # decode-only steps: the smallest legal prefill budget keeps the
        # compiled step's token axis at num_slots+1 — undiluted decode
        # occupancy, and a small program.  (Still CORRECT for the
        # re-route fallback that prefills here one token per step.)
        d_kw.setdefault("prefill_token_budget", 1)
        inner = engine_factory

        def factory(rm, mesh, i, **kw):
            role = roles[i]
            kw = dict(kw)
            if role == ROLE_PREFILL:
                kw.update(p_kw)
            elif role == ROLE_DECODE:
                kw.update(d_kw)
            kw.setdefault("role", role)
            if inner is not None:
                return inner(rm, mesh, i, **kw)
            return ServingEngine(rm, mesh=mesh, **kw)

        super().__init__(model, dp=len(roles), mp=mp, devices=devices,
                         model_factory=model_factory,
                         placement=placement or RolePlacement(),
                         engine_factory=factory, **engine_kw)
        self._page_transfer = PageTransfer(
            fault_hook=lambda p, c: self._transfer_hook(p, c))
        # transfer telemetry (docs/observability.md): cluster-labeled —
        # a transfer belongs to the hand-off fabric, not either replica
        self._transfer_totals = _tmetrics.CounterSet(
            "serving_transfer", {"pages": 0, "bytes": 0, "total": 0,
                                 "failed": 0},
            labels=self._cluster_label)
        self._transfer_hist = _tmetrics.registry().histogram(
            "serving_transfer_seconds",
            "wall seconds per committed page hand-off (reserve -> "
            "commit -> source release)",
        ).labels(**self._cluster_label)

    def _transfer_hook(self, point: str, ctx: dict):
        """The ``page_transfer`` fault point rides the cluster's injector
        (``FaultInjector.install(cluster)``), same as ``cluster_step``."""
        if self._fault_hook is not None:
            self._fault_hook(point, ctx)

    # -- role queries ------------------------------------------------------
    def role_indices(self, role: str) -> List[int]:
        return [i for i, r in enumerate(self.roles) if r == role]

    def _decode_destinations(self, src_i: int, req: Request) -> List[int]:
        """Decode replicas ranked for THIS request: LoRA residency is
        mandatory (a non-resident replica fails the tenant at adoption),
        then load, then speculative acceptance — the ROADMAP-named
        decode-side placement signals."""
        cands = []
        for i in self.role_indices(ROLE_DECODE):
            if i == src_i or not self._stepping(i):
                continue
            e = self.replicas[i]
            if e.draining or not e.scheduler.free_slot_indices():
                continue
            resident, accept = replica_signals(e, req.adapter)
            if req.adapter is not None and not resident:
                continue
            cands.append(((0 if resident else 1), replica_load(e),
                          -accept, i))
        return [c[-1] for c in sorted(cands)]

    # -- the hand-off scan -------------------------------------------------
    def run_handoffs(self) -> int:
        """Scan prefill replicas for requests whose prompt completed and
        hand each to a decode replica; returns transfers committed.  Runs
        at the START of every cluster tick (before any replica steps), so
        a copy never races the pools' own step dispatches.  A request no
        destination can take right now simply keeps decoding where it is
        — colocated fallback, never a stall."""
        moved = 0
        for si in self.role_indices(ROLE_PREFILL):
            if not self._stepping(si):
                continue
            src = self.replicas[si]
            # plan: route each ready request to its best destination,
            # spilling to the next-ranked one when a pool's free slots
            # fill up, then move each destination's group in ONE batched
            # copy (transfer_many) — the per-request ownership protocol
            # is preserved inside the batch
            plan: dict = {}
            for idx, slot in src.scheduler.seated():
                req = slot.request
                if req.state != RequestState.DECODE:
                    continue
                if slot.pending is not None and len(slot.pending):
                    continue
                for di in self._decode_destinations(si, req):
                    taken = plan.setdefault(di, [])
                    if len(taken) < len(
                            self.replicas[di].scheduler.free_slot_indices()):
                        taken.append(idx)
                        break
            for di, idxs in plan.items():
                moved += self._transfer_group(si, src, idxs, di)
        return moved

    def _transfer_group(self, si: int, src: ServingEngine,
                        idxs: List[int], di: int) -> int:
        t0 = time.monotonic()
        try:
            committed, pages, failed = self._page_transfer.transfer_many(
                src, idxs, self.replicas[di],
                src_replica=si, dst_replica=di)
        except Exception:  # noqa: BLE001 — whole-batch copy failure
            self._transfer_totals.inc("failed", len(idxs))
            return 0
        if failed:
            self._transfer_totals.inc("failed", failed)
        if not committed:
            return 0
        cache = src.cache
        self._transfer_totals.inc("pages", pages)
        self._transfer_totals.inc("bytes", page_transfer_bytes(
            pages, cache.num_heads, cache.page_size, cache.head_dim,
            num_layers=cache.num_layers, dtype=cache.dtype))
        self._transfer_totals.inc("total", committed)
        self._transfer_hist.observe(time.monotonic() - t0)
        return committed

    # -- the serving loop --------------------------------------------------
    def _replica_step(self, i: int) -> dict:
        """Decode-role replicas run ``decode_steps_per_tick`` sub-steps
        INSIDE the pooled barrier — their cheap decode-only dispatches
        overlap the prefill replicas' longer steps instead of gating on
        them.  That scheduling freedom (decode cadence decoupled from
        prompt length) is the ITL win serving_bench's ``--disagg`` sweep
        measures."""
        if self.roles[i] != ROLE_DECODE or self.decode_steps_per_tick == 1:
            return super()._replica_step(i)
        eng = self.replicas[i]
        met = eng.step()
        tokens = met["tokens_this_step"]
        for _ in range(self.decode_steps_per_tick - 1):
            met = eng.step()
            tokens += met["tokens_this_step"]
        met = dict(met)
        met["tokens_this_step"] = tokens
        return met

    def step(self) -> dict:
        """One cluster tick: hand-offs first (tick-start, before any
        replica steps, so a copy never races a pool's own dispatch),
        then the inherited tick with decode sub-stepping inside the
        barrier (``_replica_step``)."""
        transfers = self.run_handoffs()
        agg = super().step()
        agg["transfers_this_step"] = transfers
        return agg

    # -- observability -----------------------------------------------------
    def metrics(self) -> dict:
        out = super().metrics()
        out["roles"] = list(self.roles)
        t = dict(self._transfer_totals)
        out["transfer_pages"] = t["pages"]
        out["transfer_bytes"] = t["bytes"]
        out["transfers_total"] = t["total"]
        out["transfers_failed"] = t["failed"]
        return out


# ---------------------------------------------------------------------------
# per-role elasticity
# ---------------------------------------------------------------------------

class _RolePoolView:
    """One role pool of a :class:`DisaggServingEngine`, shaped like the
    cluster surface :class:`~.elastic.ElasticServingController` senses
    and actuates — replica indices are LOCAL to the pool (``indices``
    maps them back).  Unknown attributes (the brownout actuators,
    ``set_shedding``, ...) delegate to the real cluster: the rungs are
    cluster-wide, which is exactly why only ONE pool's controller may
    own them."""

    def __init__(self, cluster, indices: Sequence[int]):
        self._cluster = cluster
        self.indices = list(indices)

    @property
    def replicas(self):
        return [self._cluster.replicas[i] for i in self.indices]

    def _stepping(self, i: int) -> bool:
        return self._cluster._stepping(self.indices[i])

    @property
    def _parked(self):
        return {j for j, g in enumerate(self.indices)
                if g in self._cluster._parked}

    def activate_replica(self, i: int):
        self._cluster.activate_replica(self.indices[i])

    def begin_drain_replica(self, i: int, deadline_s: float = 5.0):
        self._cluster.begin_drain_replica(self.indices[i],
                                          deadline_s=deadline_s)

    def __getattr__(self, name):
        return getattr(self._cluster, name)


class DisaggElasticController:
    """Two PR-19 controllers over one disaggregated cluster: the prefill
    pool (prefill + colocated replicas) regulates TTFT and owns the
    brownout ladder; the decode pool regulates ITL
    (``ElasticConfig(signal="itl")``) with its ladder disabled.  Each
    pool scales up/down only among ITS replicas, from ITS SLO signal —
    independent role scaling, while drain/re-home (``begin_drain_replica``
    checkpoints re-prefill on the admitting pool via
    :class:`RolePlacement`) and the ladder compose unchanged.

    Action ``replica`` indices are pool-local; ``prefill_pool.indices``
    / ``decode_pool.indices`` translate to cluster indices."""

    def __init__(self, cluster, prefill_config: Optional[ElasticConfig]
                 = None, decode_config: Optional[ElasticConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        roles = (list(getattr(cluster, "roles", ()))
                 or [replica_role(e) for e in cluster.replicas])
        self.cluster = cluster
        self.prefill_pool = _RolePoolView(
            cluster, [i for i, r in enumerate(roles) if r != ROLE_DECODE])
        self.decode_pool = _RolePoolView(
            cluster, [i for i, r in enumerate(roles) if r == ROLE_DECODE])
        if decode_config is None:
            decode_config = ElasticConfig(signal="itl",
                                          brownout_enabled=False)
        self.prefill = ElasticServingController(
            self.prefill_pool, prefill_config, clock=clock)
        self.decode = ElasticServingController(
            self.decode_pool, decode_config, clock=clock)

    def tick(self) -> list:
        return self.prefill.tick() + self.decode.tick()

    @property
    def actions(self) -> list:
        return list(self.prefill.actions) + list(self.decode.actions)

    def close(self):
        self.prefill.close()
        self.decode.close()
