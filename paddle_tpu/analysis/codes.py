"""Graph Lint finding codes, severities, and the shared TPU tiling rules.

This module is deliberately dependency-free (no jax import): the Pallas
kernel eligibility gates (`ops/pallas_kernels/flash_attention.py`,
`decode_attention.py`) import it at kernel-module import time, and the
linter (`analysis/graph_lint.py`) uses the SAME rules — so a shape the
kernels reject for tiling reasons and a shape the linter flags as
tile-misaligned are described by one definition, with one code (GL002).

Codes are stable API: baselines (`tools/graph_lint_baseline.json`) and CI
wrappers key on them.  Adding a pass means adding a code HERE first (see
docs/graph_lint.md "how to add a pass").
"""
from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = [
    "CODES", "SEVERITY_RANK", "TILE_SUBLANE", "TILE_LANE",
    "misaligned_dims", "padded_shape", "padding_waste_elems",
    "default_block", "GateReason", "flash_gate_reason",
    "decode_gate_reason", "paged_gate_reason", "ragged_gate_reason",
    "mesh_shard_gate_reason",
]

# code -> (short name, default severity).  Severities: "error" (correctness
# or a hard perf cliff), "warning" (perf/memory hazard worth a human look),
# "info" (advisory; never fails the CI gate).
CODES = {
    "GL001": ("dtype-promotion", "error"),
    "GL002": ("tile-misalignment", "warning"),
    "GL003": ("host-sync", "error"),
    "GL004": ("donation-miss", "warning"),
    "GL005": ("dead-code", "warning"),
    "GL006": ("intermediate-blowup", "warning"),
    "GL007": ("retrace-churn", "warning"),
    # v3 (SPMD/communication passes — see docs/graph_lint.md "v3"):
    "GL008": ("unoverlapped-collective", "warning"),
    "GL009": ("replication-blowup", "warning"),
    "GL010": ("collective-payload-misalignment", "warning"),
    "GL011": ("degenerate-collective", "info"),
}

SEVERITY_RANK = {"error": 3, "warning": 2, "info": 1}

# The TPU vector-register tile for fp32: 8 sublanes x 128 lanes.  A dim
# smaller than one tile is padded once and is not actionable; a LARGER dim
# that is not a tile multiple wastes a partial tile per row/column of
# tiles, so only dims beyond the tile size count as misaligned.
TILE_SUBLANE = 8
TILE_LANE = 128


def misaligned_dims(shape) -> List[Tuple[int, int, int]]:
    """(axis, dim, tile) for each trailing dim of ``shape`` that exceeds
    its (8, 128) tile but is not a multiple of it."""
    out = []
    n = len(shape)
    if n >= 1:
        d = int(shape[-1])
        if d > TILE_LANE and d % TILE_LANE:
            out.append((n - 1, d, TILE_LANE))
    if n >= 2:
        d = int(shape[-2])
        if d > TILE_SUBLANE and d % TILE_SUBLANE:
            out.append((n - 2, d, TILE_SUBLANE))
    return out


def _ceil_to(d: int, m: int) -> int:
    return -(-int(d) // m) * m


def default_block(s: int, cap: int = 512) -> int:
    """The historical hard-coded block choice shared by every Pallas
    kernel's no-table fallback AND the autotuner's seeded defaults
    (``autotune.default_params`` / ``tools/autotune.py --seed``): halve
    ``min(cap, s)`` until it divides ``s``, then floor at 128 when 128
    still divides.  ONE implementation so a tuned fallback can't drift
    from what the seeded table entries record."""
    s = int(s)
    b = min(cap, s)
    while s % b:
        b //= 2
    return max(b, 128) if s % max(b, 128) == 0 else b


def padded_shape(shape) -> Tuple[int, ...]:
    """The (8, 128)-tile-padded layout shape the TPU actually materializes
    for ``shape``: last dim rounded up to a lane multiple (128), second-
    minor rounded up to a sublane multiple (8).  Scalars/empty shapes are
    returned unchanged.  Shared by the GL002 cost annotation and the
    roofline cost model (`analysis/cost_model.py`) so "padding waste" means one
    thing everywhere."""
    shape = tuple(int(d) for d in shape)
    if not shape:
        return shape
    out = list(shape)
    if out[-1] > 0:
        out[-1] = _ceil_to(out[-1], TILE_LANE)
    if len(out) >= 2 and out[-2] > 0:
        out[-2] = _ceil_to(out[-2], TILE_SUBLANE)
    return tuple(out)


def padding_waste_elems(shape) -> int:
    """Elements of pure tile padding in ``shape``'s physical layout:
    prod(padded_shape) - prod(shape).  Multiply by the dtype's itemsize
    for bytes."""
    shape = tuple(int(d) for d in shape)
    if not shape:
        return 0
    n = 1
    p = 1
    for d, pd in zip(shape, padded_shape(shape)):
        n *= d
        p *= pd
    return max(p - n, 0)


class GateReason:
    """Structured 'why the Pallas kernel was not used' carrying the lint
    code — the one formatting both the kernels' fallback logs and the
    linter's GL002 findings use."""

    __slots__ = ("code", "kernel", "detail")

    def __init__(self, code: str, kernel: str, detail: str):
        self.code = code
        self.kernel = kernel
        self.detail = detail

    def __str__(self) -> str:
        name = CODES.get(self.code, ("", ""))[0]
        return f"[{self.code} {name}] {self.kernel}: {self.detail}"

    def __repr__(self) -> str:
        return f"GateReason({self.code!r}, {self.kernel!r}, {self.detail!r})"


def _attention_gate(seq_len: int, head_dim: int, kernel: str,
                    seq_name: str) -> Optional[GateReason]:
    problems = []
    if seq_len < TILE_LANE or seq_len % TILE_LANE:
        problems.append(
            f"{seq_name}={seq_len} is not a {TILE_LANE}-multiple >= "
            f"{TILE_LANE} (KV blocking)")
    if head_dim % 64:
        problems.append(f"head_dim={head_dim} is not a 64-multiple "
                        "(MXU contraction width)")
    if not problems:
        return None
    return GateReason("GL002", kernel, "; ".join(problems))


def flash_gate_reason(seq_len: int, head_dim: int) -> Optional[GateReason]:
    """None when the training flash kernel accepts the shape, else the
    GL002-coded reason it falls back to XLA."""
    return _attention_gate(seq_len, head_dim, "flash_attention", "seq_len")


def decode_gate_reason(max_seq: int, head_dim: int) -> Optional[GateReason]:
    """None when the q-len-1 flash-decode kernel accepts the cache shape,
    else the GL002-coded reason it falls back to XLA."""
    return _attention_gate(max_seq, head_dim, "decode_attention", "max_seq")


def _shard_problems(num_heads: Optional[int], mp: int) -> List[str]:
    """The mesh-shard preconditions of the per-head paged/ragged kernel
    partition (serving over the ``mp`` axis): the head axis must split
    evenly, with at least one head per shard.  Shared by the kernel gates
    (when asked with the shard geometry) and the serving engine's
    construction-time validation — a violation is reported as a typed
    GL002-style reason instead of a shard_map crash."""
    problems: List[str] = []
    mp = int(mp)
    if mp <= 1 or num_heads is None:
        return problems
    num_heads = int(num_heads)
    if num_heads % mp:
        problems.append(
            f"num_heads={num_heads} is not divisible by mp={mp} "
            "(per-head pool shard)")
    elif num_heads // mp < 1:
        problems.append(
            f"num_heads={num_heads} leaves no head per shard at mp={mp}")
    return problems


def mesh_shard_gate_reason(num_heads: int, mp: int,
                           kernel: str = "ragged_paged_attention"
                           ) -> Optional[GateReason]:
    """None when the per-head ``mp`` partition of ``kernel`` can exist,
    else the GL002-coded reason.  This is the HARD precondition the
    serving engine checks at construction: unlike the tile rules (which
    only cost the Pallas kernel and fall back to XLA), an indivisible head
    axis cannot be sharded at all."""
    problems = _shard_problems(num_heads, mp)
    if not problems:
        return None
    return GateReason("GL002", kernel, "; ".join(problems))


def paged_gate_reason(page_size: int, head_dim: int,
                      num_heads: Optional[int] = None,
                      mp: int = 1) -> Optional[GateReason]:
    """None when the paged decode-attention kernel accepts the block-pool
    shape, else the GL002-coded reason it falls back to the XLA gather
    reference.  A KV page is one kernel block, so the same tiling rules
    apply to ``page_size`` that the contiguous decode kernel applies to its
    KV blocking of ``max_seq``.  With ``mp > 1`` (the mesh-sharded serving
    pool) the per-head shard preconditions are checked too: the head axis
    must split evenly, and the per-SHARD layout still obeys the same
    head_dim/tile rules (head_dim is never split, so those are
    unchanged)."""
    base = _attention_gate(page_size, head_dim, "paged_attention",
                           "page_size")
    problems = [base.detail] if base is not None else []
    problems += _shard_problems(num_heads, mp)
    if not problems:
        return None
    return GateReason("GL002", "paged_attention", "; ".join(problems))


def ragged_gate_reason(page_size: int, head_dim: int,
                       token_block: int = 8,
                       num_heads: Optional[int] = None,
                       mp: int = 1) -> Optional[GateReason]:
    """None when the ragged paged-attention kernel accepts the (pool,
    work-list) layout, else the GL002-coded reason it falls back to the
    XLA gather reference.  Pool rules are the paged kernel's verbatim (a
    page is one KV block); the query token block additionally must be a
    sublane multiple — the q rows of every work item form one (8, 128)
    tile column.  With ``mp > 1`` the per-head shard preconditions apply
    (see :func:`paged_gate_reason`)."""
    base = _attention_gate(page_size, head_dim, "ragged_paged_attention",
                           "page_size")
    problems = [base.detail] if base is not None else []
    if token_block < TILE_SUBLANE or token_block % TILE_SUBLANE:
        problems.append(
            f"token_block={token_block} is not an {TILE_SUBLANE}-multiple "
            f">= {TILE_SUBLANE} (query sublane rows)")
    problems += _shard_problems(num_heads, mp)
    if not problems:
        return None
    return GateReason("GL002", "ragged_paged_attention",
                      "; ".join(problems))


# one line per DISTINCT reason (kernel + shape) per process: a decode loop
# hitting the gate every step must not spam stderr.  Bounded: a varlen
# workload probing a new unaligned length per batch would otherwise grow
# the set (and the log) forever — past the cap the gate saturates silently
# (same discipline as core/op_cache's _SHAPE_KEY_CAP).
_SEEN_FALLBACKS: set = set()
_SEEN_FALLBACKS_CAP = 64


def note_fallback(reason: GateReason, stream=None):
    """Record a kernel's XLA fallback with its structured reason, once per
    distinct (kernel, detail) up to a cap.  The Pallas eligibility gates
    call this on TPU hosts so a silently-slower fallback is visible in
    stderr with the same GL002 formatting the linter uses."""
    key = str(reason)
    if key in _SEEN_FALLBACKS or len(_SEEN_FALLBACKS) >= _SEEN_FALLBACKS_CAP:
        return
    _SEEN_FALLBACKS.add(key)
    import sys

    (stream or sys.stderr).write(
        f"[paddle_tpu.graph_lint] {reason}; falling back to the XLA "
        "expression\n")
