"""Continuous-batching serving engine over a paged KV cache.

The serving analog of the reference's fused_multi_transformer serving stack,
TPU-native: one fixed-shape compiled decode step serves an ever-changing
request mix (PAPERS.md: "Ragged Paged Attention", arxiv 2604.15464).

- :mod:`paged_cache` — the global KV page pool (``PagedKVCache``) and the
  free-list ``BlockAllocator`` (page 0 reserved as the null page);
- :mod:`admission` — the per-replica scheduler: fixed decode slots,
  admission with up-front page reservation (out-of-pages admission
  backpressures into the queue), immediate page free on retirement
  (:mod:`scheduler` remains the compatibility facade);
- :mod:`prefix_cache` — the global prefix cache (``PrefixCache``):
  completed full pages radix-indexed by token-id chunks, spliced
  copy-on-write into later admissions' page tables so only the uncached
  tail prefills; LRU eviction of refcount-0 pages under pool pressure —
  docs/serving.md "Prefix cache";
- :mod:`placement` — the cluster-level scheduler: which ``dp`` replica
  seats a request (least-loaded, queue-depth backpressure signal; typed
  shed only when ALL replicas backpressure);
- :mod:`sharded` — ``ShardedServingEngine``: ``dp`` replica engines x
  ``mp`` tensor-parallel chips (per-head-sharded pool + shard_map'd
  ragged kernels + column/row-parallel weights) behind one placement
  scheduler — docs/serving.md "Sharded serving";
- :mod:`engine` — ``ServingEngine`` / ``RequestQueue``: request lifecycle
  (SUBMITTED -> PREFILL -> DECODE -> DONE | CANCELLED | TIMED_OUT |
  FAILED), chunked prefill into pages, ONE donated retrace-free jitted
  decode step over all slots, per-request sampling + deadlines +
  cancellation, watchdog-supervised steps with auto-recovery, bounded
  queues with typed ``Overloaded`` shedding, NaN-slot quarantine,
  streaming token callbacks, per-step metrics;
- :mod:`faults` — deterministic fault-injection harness (step crashes,
  stalls, NaN logits, pool exhaustion, callback errors) driving
  tests/test_serving_faults.py and tools/serving_fault_gate.py;
- :mod:`speculative` — ``SpeculativeEngine``: draft-model propose +
  ONE fused verify dispatch with in-graph accept/reject (greedy
  bit-identical to the plain engine; sampling preserves the target
  distribution exactly), draft pages under the allocator's
  speculative-reservation/rollback API;
- :mod:`lora` — ``LoRAAdapterPool``: paged per-request adapter slabs
  gathered per token inside the step — one compiled program serves
  many fine-tuned tenants, register/evict at runtime without retraces.

- :mod:`elastic` — ``ElasticServingController``: the closed loop over
  all of the above — windowed SLO sensing from the telemetry registry,
  deterministic hysteresis/cooldown policy emitting typed
  ScaleUp/ScaleDown/Brownout/Recover actions, graceful replica drain
  with token-prefix checkpoint re-homing, and the ordered brownout
  ladder — docs/serving.md "Elasticity & degradation ladder";
- :mod:`disagg` — ``DisaggServingEngine``: disaggregated serving —
  dedicated prefill and decode replica roles with page-granular KV
  hand-off (``PageTransfer``: destination reservation -> batched
  device-to-device page copy -> atomic commit -> source release, exact
  on both allocators under mid-transfer faults), role-aware admission
  (``RolePlacement``) and per-role elastic scaling
  (``DisaggElasticController``: TTFT drives the prefill pool, ITL the
  decode pool) — docs/serving.md "Disaggregated prefill/decode".

See docs/serving.md (incl. the "Failure model & SLOs" section).
"""
from .elastic import (  # noqa: F401
    BROWNOUT_RUNGS,
    Brownout,
    ClusterSignals,
    ElasticConfig,
    ElasticServingController,
    Recover,
    ScaleDown,
    ScaleUp,
    SLOTargets,
)
from .disagg import (  # noqa: F401
    ROLE_COLOCATED,
    ROLE_DECODE,
    ROLE_PREFILL,
    DisaggElasticController,
    DisaggServingEngine,
    PageTransfer,
    PageTransferAborted,
    RolePlacement,
)
from .engine import (  # noqa: F401
    DeadlineExceeded,
    NaNLogitsError,
    Overloaded,
    Request,
    RequestCancelled,
    RequestQueue,
    RequestState,
    SamplingParams,
    ServingEngine,
    ServingError,
    StepStalledError,
    serve_trace_counts,
    reset_serve_trace_counts,
)
from .faults import (  # noqa: F401
    FaultInjector,
    FaultPlan,
    InjectedFault,
    random_schedule,
    random_transfer_schedule,
)
from .lora import (  # noqa: F401
    AdapterError,
    AdapterInUse,
    LoRAAdapterPool,
    UnknownAdapter,
    random_adapter,
)
from .paged_cache import (  # noqa: F401
    NULL_PAGE,
    BlockAllocator,
    PagedKVCache,
    pages_for_tokens,
)
from .prefix_cache import PrefixCache  # noqa: F401
from .speculative import SpeculativeEngine  # noqa: F401
from .scheduler import (  # noqa: F401
    AdmissionScheduler,
    LeastLoadedPlacement,
    PlacementScheduler,
    PrefixLocalityPlacement,
    Scheduler,
    Slot,
    replica_load,
)
from .sharded import ShardedServingEngine  # noqa: F401

__all__ = [
    "Request", "RequestQueue", "RequestState", "SamplingParams",
    "ServingEngine", "ShardedServingEngine", "SpeculativeEngine",
    "LoRAAdapterPool", "AdapterError", "AdapterInUse", "UnknownAdapter",
    "random_adapter",
    "serve_trace_counts", "reset_serve_trace_counts",
    "ServingError", "Overloaded", "DeadlineExceeded", "RequestCancelled",
    "StepStalledError", "NaNLogitsError",
    "FaultInjector", "FaultPlan", "InjectedFault", "random_schedule",
    "random_transfer_schedule",
    "DisaggServingEngine", "DisaggElasticController", "RolePlacement",
    "PageTransfer", "PageTransferAborted",
    "ROLE_PREFILL", "ROLE_DECODE", "ROLE_COLOCATED",
    "NULL_PAGE", "BlockAllocator", "PagedKVCache", "pages_for_tokens",
    "PrefixCache",
    "AdmissionScheduler", "Scheduler", "Slot",
    "PlacementScheduler", "LeastLoadedPlacement",
    "PrefixLocalityPlacement", "replica_load",
    "ElasticServingController", "ElasticConfig", "ClusterSignals",
    "SLOTargets", "ScaleUp", "ScaleDown", "Brownout", "Recover",
    "BROWNOUT_RUNGS",
]
