"""Benchmark: GPT train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline metric is tokens/sec/chip on the flagship GPT train step
(fwd + bwd + AdamW fused into a single XLA program via jit.to_static),
with MFU derived from the Megatron FLOPs formula. vs_baseline compares
MFU against the 45% north-star target (BASELINE.json: "GPT-3 1.3B
hybrid-parallel trains at >=45% MFU ... zero CUDA deps").
"""
import json
import os
import sys
import time

import numpy as np

# bf16 matmuls for the MXU: the bench path uses AMP O1 (reference
# amp_guard list-based casting), so keep default matmul precision.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")


def _peak_flops_per_chip(device_kind: str) -> float:
    """bf16 peak FLOP/s by TPU generation (public spec sheet numbers).

    device_kind strings vary ('TPU v5', 'TPU v5 lite', 'TPU v5p', ...);
    'lite' marks the e-class parts, bare v5 is v5p-class."""
    gen = (os.environ.get("PALLAS_AXON_TPU_GEN", "") or "").lower()
    kind = (device_kind or "").lower()
    for probe in (gen, kind):
        if not probe:
            continue
        if "v6" in probe:
            return 918e12
        if "v5e" in probe or ("v5" in probe and "lite" in probe):
            return 197e12
        if "v5" in probe:
            return 459e12
        if "v4" in probe:
            return 275e12
        if "v3" in probe:
            return 123e12
        if "v2" in probe:
            return 45e12
    return 197e12  # conservative default (v5e class)


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_small,
    )

    on_tpu = jax.devices()[0].platform != "cpu"
    # CPU fallback uses a toy shape so the bench always completes
    if on_tpu:
        batch, seq = 8, 1024
        cfg = gpt_small(hidden_dropout=0.0, attention_dropout=0.0)
        steps = 10
    else:
        batch, seq = 2, 128
        cfg = gpt_small(hidden_dropout=0.0, attention_dropout=0.0)
        cfg.num_layers = 2
        steps = 3

    pt.seed(0)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")

    @pt.jit.to_static
    def train_step(ids, labels):
        with pt.amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            loss = crit(model(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # warmup (eager) + scout/compile + 1 compiled call
    for _ in range(3):
        loss = train_step(ids, labels)
    float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step(ids, labels)
    final = float(loss)  # forces completion of the async chain
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"bench diverged: loss={final}"

    tokens_per_sec = batch * seq * steps / dt

    # Megatron-LM FLOPs/iteration: 72 b s L h^2 (1 + s/(6h) + V/(12 L h))
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    flops_per_iter = 72 * batch * seq * L * h * h * (1 + seq / (6 * h) + V / (12 * L * h))
    model_flops_per_sec = flops_per_iter * steps / dt
    peak = _peak_flops_per_chip(getattr(jax.devices()[0], "device_kind", ""))
    mfu = model_flops_per_sec / peak

    print(json.dumps({
        "metric": "gpt_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": f"tokens/s (bs={batch} seq={seq} mfu={mfu:.3f} on {'tpu' if on_tpu else 'cpu'})",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
