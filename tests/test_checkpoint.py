"""Crash-consistent checkpointing: atomic io, manager crash injection,
async writer, bit-deterministic resume, bad-step sentry, preemption, and
hapi/Engine integration (ISSUE 4; reference dist_saver.py + fleet elastic
restart contract)."""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.checkpoint import (
    BadStepSentry,
    CheckpointError,
    CheckpointManager,
    GracefulExit,
    PreemptionHandler,
    TrainState,
    all_finite,
)
from paddle_tpu.checkpoint.manager import MANIFEST_NAME, PAYLOAD_NAME


# ---------------------------------------------------------------------------
# framework.io atomic save/load
# ---------------------------------------------------------------------------

class TestAtomicIO:
    def test_truncated_file_raises_clear_error(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        pt.save({"w": pt.to_tensor(np.arange(1000, dtype=np.float32))}, path)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(RuntimeError, match="truncated or corrupt"):
            pt.load(path)

    def test_failed_save_preserves_previous_content(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        pt.save({"v": 1}, path)

        class Boom:
            def __reduce__(self):
                raise RuntimeError("mid-serialization crash")

        with pytest.raises(RuntimeError, match="mid-serialization"):
            pt.save({"v": 2, "bad": Boom()}, path)
        # the old file is intact and no temp junk was left behind
        assert pt.load(path)["v"] == 1
        leftovers = [n for n in os.listdir(tmp_path) if ".tmp" in n]
        assert leftovers == []

    def test_roundtrip_tensors(self, tmp_path):
        path = str(tmp_path / "t.pd")
        t = pt.to_tensor(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        pt.save({"a": t, "n": 5}, path)
        out = pt.load(path)
        np.testing.assert_array_equal(out["a"].numpy(), t.numpy())
        assert out["n"] == 5


# ---------------------------------------------------------------------------
# CheckpointManager: crash injection, validation fallback, retention
# ---------------------------------------------------------------------------

def _tree(step):
    return {"w": np.full((8,), float(step), np.float32), "step": step}


class TestManagerCrashConsistency:
    INJECTION_POINTS = ("after_tmpdir", "mid_payload", "after_payload",
                       "before_manifest", "before_commit")

    @pytest.mark.parametrize("point", INJECTION_POINTS)
    def test_interrupted_write_never_selected(self, tmp_path, point):
        """A writer killed at ANY stage leaves garbage latest() skips."""
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)

        def boom(p):
            if p == point:
                raise KeyboardInterrupt(f"crash at {p}")

        m._fault_hook = boom
        with pytest.raises(KeyboardInterrupt):
            m.save(_tree(2), step=2)
        m._fault_hook = None
        info = m.latest()
        assert info is not None and info.step == 1
        tree, manifest = m.restore(info)
        np.testing.assert_array_equal(tree["w"], _tree(1)["w"])
        assert manifest["step"] == 1

    def test_hand_truncated_payload_falls_back(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)
        m.save(_tree(2), step=2)
        p = tmp_path / "ckpt-00000002" / PAYLOAD_NAME
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        assert m.latest().step == 1

    def test_corrupt_payload_byte_falls_back(self, tmp_path):
        """Same size, flipped byte: only the digest catches it."""
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)
        m.save(_tree(2), step=2)
        p = tmp_path / "ckpt-00000002" / PAYLOAD_NAME
        raw = bytearray(p.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        p.write_bytes(bytes(raw))
        assert m.latest().step == 1

    def test_corrupt_manifest_falls_back(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)
        m.save(_tree(2), step=2)
        (tmp_path / "ckpt-00000002" / MANIFEST_NAME).write_text("{not json")
        assert m.latest().step == 1

    def test_missing_manifest_falls_back(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)
        m.save(_tree(2), step=2)
        os.unlink(tmp_path / "ckpt-00000002" / MANIFEST_NAME)
        assert m.latest().step == 1

    def test_no_valid_checkpoint(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=False)
        assert m.latest() is None
        with pytest.raises(CheckpointError, match="no valid checkpoint"):
            m.restore()

    def test_stale_tmp_dirs_cleaned_on_init(self, tmp_path):
        stale = tmp_path / ".tmp-ckpt-00000009-99999-deadbeef"
        stale.mkdir()
        (stale / PAYLOAD_NAME).write_bytes(b"partial")
        m = CheckpointManager(str(tmp_path), async_save=False)
        assert not stale.exists()
        assert m.latest() is None

    def test_keep_last_k_retention(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_last_k=2, async_save=False)
        for s in range(1, 6):
            m.save(_tree(s), step=s)
        steps = [c.step for c in m.checkpoints()]
        assert steps == [5, 4]

    def test_gc_never_deletes_only_valid(self, tmp_path):
        """Newest checkpoints corrupted: GC must keep the older valid one
        (it is the fallback) while sweeping the invalid garbage."""
        m0 = CheckpointManager(str(tmp_path), keep_last_k=3, async_save=False)
        m0.save(_tree(1), step=1)
        m0.save(_tree(2), step=2)
        m0.save(_tree(3), step=3)
        for s in (2, 3):
            p = tmp_path / f"ckpt-0000000{s}" / PAYLOAD_NAME
            raw = bytearray(p.read_bytes())
            raw[0] ^= 0xFF
            p.write_bytes(bytes(raw))
        m = CheckpointManager(str(tmp_path), keep_last_k=1, async_save=False)
        m._gc()
        assert m.latest().step == 1
        assert not (tmp_path / "ckpt-00000002").exists()
        assert not (tmp_path / "ckpt-00000003").exists()

    def test_resave_same_step(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=False)
        m.save(_tree(1), step=1)
        m.save({"w": np.zeros(3, np.float32)}, step=1)
        tree, _ = m.restore()
        assert tree["w"].shape == (3,)

    def test_failed_write_leaves_no_staging_dir(self, tmp_path):
        """Transient writer errors (ENOSPC-class) must not leak
        full-payload .tmp dirs over a long-lived trainer."""
        m = CheckpointManager(str(tmp_path), async_save=False)
        m._fault_hook = lambda p: (_ for _ in ()).throw(OSError("disk full"))
        with pytest.raises(OSError):
            m.save(_tree(1), step=1)
        assert [n for n in os.listdir(tmp_path) if n.startswith(".tmp")] == []

    def test_step_ordering_beyond_zero_pad(self, tmp_path):
        """Steps past 8 digits must still order numerically, not
        lexicographically."""
        m = CheckpointManager(str(tmp_path), keep_last_k=2,
                              async_save=False)
        m.save(_tree(1), step=99999999)
        m.save(_tree(2), step=100000000)
        assert m.latest().step == 100000000
        assert [c.step for c in m.checkpoints()] == [100000000, 99999999]


class TestAsyncWriter:
    def test_async_save_and_wait(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=True)
        m.save(_tree(1), step=1)
        m.wait()
        assert m.latest().step == 1

    def test_writer_error_reraised_on_next_call(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_save=True)
        m._fault_hook = lambda p: (_ for _ in ()).throw(OSError("disk full"))
        m.save(_tree(1), step=1)  # returns immediately; writer dies
        with pytest.raises(CheckpointError, match="disk full"):
            m.wait()
        m._fault_hook = None
        m.save(_tree(2), step=2)  # error was consumed; next save is clean
        m.wait()
        assert m.latest().step == 2

    def test_at_most_one_inflight(self, tmp_path):
        """A second save() drains the first write before starting."""
        m = CheckpointManager(str(tmp_path), async_save=True)
        release = threading.Event()
        entered = threading.Event()
        order = []

        def hook(p):
            if p == "before_commit":
                entered.set()
                order.append("blocked")
                release.wait(timeout=10)

        m._fault_hook = hook
        m.save(_tree(1), step=1)
        assert entered.wait(timeout=10)  # writer is parked at the commit
        m._fault_hook = None
        threading.Timer(0.3, release.set).start()
        t0 = time.monotonic()
        m.save(_tree(2), step=2)  # must join the blocked writer first
        assert time.monotonic() - t0 > 0.1
        m.wait()
        assert order == ["blocked"]
        assert [c.step for c in m.checkpoints()] == [2, 1]

    def test_async_step_overhead_small(self, tmp_path):
        """Acceptance micro-check: the step path pays only the host
        snapshot — serialization+fsync happen off-thread.  (Full numbers:
        tools/ckpt_bench.py.)"""
        state = {"w": np.random.RandomState(0).randn(256, 256).astype(np.float32)}
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            np.sum(state["w"])  # stand-in train step
        base = time.perf_counter() - t0
        m = CheckpointManager(str(tmp_path), keep_last_k=2, async_save=True)
        t0 = time.perf_counter()
        for s in range(n):
            np.sum(state["w"])
            m.save(dict(state), step=s)
        with_ckpt = time.perf_counter() - t0
        m.wait()
        # generous CI bound: the non-blocking save path must not cost
        # orders of magnitude over the bare loop
        assert with_ckpt < base + 5.0
        assert m.latest() is not None


# ---------------------------------------------------------------------------
# TrainState: bit-deterministic resume on a GPT train loop
# ---------------------------------------------------------------------------

def _gpt_setup(seed=7):
    from paddle_tpu.models import (
        GPTForPretraining, GPTPretrainingCriterion, gpt_tiny,
    )

    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)), dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)), dtype="int64")
    crit = GPTPretrainingCriterion(cfg)
    pt.seed(seed)
    m = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    return cfg, m, opt, crit, ids, labels


def _gpt_step(m, opt, crit, ids, labels):
    loss = crit(m(ids), labels)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


class TestDeterministicResume:
    def test_gpt_resume_bitwise(self, tmp_path):
        """train(6) == train(3); checkpoint; restore into a FRESH model;
        train(3) — losses bitwise identical (params, Adam moments + beta
        powers, RNG all restored)."""
        _, m, opt, crit, ids, labels = _gpt_setup()
        ref = [_gpt_step(m, opt, crit, ids, labels) for _ in range(6)]

        _, m2, o2, crit, ids, labels = _gpt_setup()
        pre = [_gpt_step(m2, o2, crit, ids, labels) for _ in range(3)]
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(TrainState(m2, o2).capture(position={"step": 3}), step=3)
        mgr.wait()

        _, m3, o3, crit, ids, labels = _gpt_setup(seed=999)  # different init
        tree, _ = mgr.restore()
        pos = TrainState(m3, o3).restore(tree)
        assert pos == {"step": 3}
        post = [_gpt_step(m3, o3, crit, ids, labels) for _ in range(3)]
        assert pre == ref[:3]
        assert post == ref[3:]  # exact float equality — bitwise resume

    def test_adam_aux_state_roundtrip(self):
        """Adam's beta-power accumulators must survive
        state_dict/set_state_dict (they were saved but never restored)."""
        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=lin.parameters())
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(3):
            loss = lin(x).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        assert "aux_0" in sd
        lin2 = pt.nn.Linear(4, 4)
        opt2 = pt.optimizer.AdamW(learning_rate=1e-3,
                                  parameters=lin2.parameters())
        opt2.set_state_dict(sd)
        np.testing.assert_array_equal(
            np.asarray(opt2._aux_state[0]._value),
            np.asarray(opt._aux_state[0]._value))
        np.testing.assert_array_equal(
            np.asarray(opt2._aux_state[1]._value),
            np.asarray(opt._aux_state[1]._value))

    def test_scaler_and_scheduler_state_roundtrip(self, tmp_path):
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.optimizer.lr import CosineAnnealingDecay

        lin = pt.nn.Linear(4, 4)
        sched = CosineAnnealingDecay(learning_rate=0.1, T_max=10)
        opt = pt.optimizer.AdamW(learning_rate=sched,
                                 parameters=lin.parameters())
        scaler = GradScaler(init_loss_scaling=128.0)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(4):
            loss = scaler.scale(lin(x).mean())
            loss.backward()
            scaler.step(opt)
            opt.clear_grad()
            sched.step()
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        st = TrainState(lin, opt, scaler=scaler)
        mgr.save(st.capture(), step=4)

        lin2 = pt.nn.Linear(4, 4)
        sched2 = CosineAnnealingDecay(learning_rate=0.1, T_max=10)
        opt2 = pt.optimizer.AdamW(learning_rate=sched2,
                                  parameters=lin2.parameters())
        scaler2 = GradScaler(init_loss_scaling=2.0**15)
        tree, _ = mgr.restore()
        TrainState(lin2, opt2, scaler=scaler2).restore(tree)
        assert sched2.last_epoch == sched.last_epoch
        assert sched2.last_lr == sched.last_lr
        assert scaler2.get_loss_scaling() == scaler.get_loss_scaling()

    def test_rng_state_restored(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        pt.seed(42)
        pt.rand([4])  # advance the stream
        st = TrainState(include_rng=True)
        mgr.save(st.capture(), step=1)
        a = pt.rand([8]).numpy()
        tree, _ = mgr.restore()
        st.restore(tree)
        b = pt.rand([8]).numpy()
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Bad-step sentry + fused GradScaler check
# ---------------------------------------------------------------------------

class TestSentry:
    def test_all_finite(self):
        assert all_finite([np.ones(3), pt.to_tensor(np.zeros((2, 2)))])
        assert not all_finite([np.ones(3), np.array([1.0, np.nan])])
        assert not all_finite([np.array([np.inf])])
        assert all_finite([np.array([1, 2, 3])])  # ints are always finite
        assert all_finite([])

    def test_guard_step_skips_nan(self):
        import jax.numpy as jnp

        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        sentry = BadStepSentry(max_consecutive_bad=10)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        lin(x).mean().backward()
        w0 = np.asarray(lin.weight._value).copy()
        for p in opt._parameter_list:
            if p.grad is not None:
                p.grad._set_value(p.grad._value * jnp.nan)
        assert sentry.guard_step(opt) is False
        np.testing.assert_array_equal(np.asarray(lin.weight._value), w0)
        assert sentry.stats["bad_steps"] == 1
        opt.clear_grad()
        lin(x).mean().backward()
        assert sentry.guard_step(opt) is True
        assert sentry.stats["consecutive_bad"] == 0
        assert not np.array_equal(np.asarray(lin.weight._value), w0)

    def test_rollback_after_n_bad_steps(self, tmp_path):
        import jax.numpy as jnp

        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        state = TrainState(lin, opt)
        mgr.save(state.capture(), step=1)
        good_w = np.asarray(lin.weight._value).copy()
        # poison the live params so a rollback is observable
        lin.weight._set_value(lin.weight._value + 100.0)
        sentry = BadStepSentry(max_consecutive_bad=3, manager=mgr,
                               train_state=state)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(3):
            lin(x).mean().backward()
            for p in opt._parameter_list:
                if p.grad is not None:
                    p.grad._set_value(p.grad._value * jnp.nan)
            assert sentry.guard_step(opt) is False
            opt.clear_grad()
        assert sentry.stats["rollbacks"] == 1
        assert sentry.stats["bad_steps"] == 3
        np.testing.assert_array_equal(np.asarray(lin.weight._value), good_w)

    def test_grad_scaler_fused_semantics(self):
        """The fused unscale keeps the reference bookkeeping: NaN grads
        set found_inf, skip the step, and halve the dynamic scale."""
        import jax.numpy as jnp
        from paddle_tpu.amp import GradScaler

        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        scaler = GradScaler(init_loss_scaling=256.0)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        # good step: grads get unscaled by 1/scale
        scaler.scale(lin(x).mean()).backward()
        g_scaled = np.asarray(
            next(p.grad for p in opt._parameter_list
                 if p.grad is not None)._value).copy()
        scaler.unscale_(opt)
        assert scaler._found_inf is False
        g_unscaled = np.asarray(
            next(p.grad for p in opt._parameter_list
                 if p.grad is not None)._value)
        np.testing.assert_allclose(g_unscaled, g_scaled / 256.0, rtol=1e-6)
        opt.clear_grad()
        # bad step: nan grad -> found_inf, param frozen, scale halved
        scaler.scale(lin(x).mean()).backward()
        for p in opt._parameter_list:
            if p.grad is not None:
                p.grad._set_value(p.grad._value * jnp.nan)
        w0 = np.asarray(lin.weight._value).copy()
        scaler.step(opt)
        np.testing.assert_array_equal(np.asarray(lin.weight._value), w0)
        assert scaler.get_loss_scaling() == 128.0
        opt.clear_grad()

    def test_grad_scaler_no_grads(self):
        from paddle_tpu.amp import GradScaler

        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        scaler = GradScaler(init_loss_scaling=256.0)
        scaler.unscale_(opt)  # nothing accumulated: no crash, no found_inf
        assert scaler._found_inf is False


# ---------------------------------------------------------------------------
# Preemption: SIGTERM -> checkpoint at step boundary -> clean exit
# ---------------------------------------------------------------------------

class TestPreemption:
    def test_signal_sets_request_and_uninstall_restores(self):
        prev = signal.getsignal(signal.SIGTERM)
        h = PreemptionHandler()
        with h:
            assert not h.requested
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5
            while not h.requested and time.time() < deadline:
                time.sleep(0.01)
            assert h.requested
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_checkpoint_and_exit(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        lin = pt.nn.Linear(2, 2)
        state = TrainState(lin)
        h = PreemptionHandler()
        # not requested: no-op
        h.checkpoint_and_exit_if_requested(mgr, state, step=1)
        assert mgr.latest() is None
        h.request()
        with pytest.raises(SystemExit) as exc:
            h.checkpoint_and_exit_if_requested(mgr, state, step=7, epoch=2)
        assert exc.value.code == 0
        info = mgr.latest()
        assert info.step == 7 and info.epoch == 2
        assert info.manifest["meta"]["preempted"] is True
        tree, _ = mgr.restore(info)
        assert tree["position"] == {"epoch": 2, "step": 7}

    def test_elastic_on_change_requests_checkpoint(self):
        """Membership change through ElasticManager.chain_on_change fires
        the preemption request (the restart half of the contract)."""
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        class FakeStore:
            def __init__(self):
                self.kv = {}

            def add(self, k, v):
                self.kv[k] = self.kv.get(k, 0) + v
                return self.kv[k]

            def check(self, k):
                return k in self.kv

        store = FakeStore()
        user_calls = []
        mgr = ElasticManager(store, rank=0, nnodes=2, max_nodes=2,
                             ttl=60.0, interval=60.0,
                             on_change=lambda m: user_calls.append(m))
        h = PreemptionHandler()
        mgr.chain_on_change(h.as_elastic_on_change())
        store.add("elastic/beat/0", 1)
        mgr.alive_nodes()          # first computation: recorded silently
        store.add("elastic/beat/1", 1)
        assert sorted(mgr.alive_nodes()) == [0, 1]  # change -> both fire
        assert user_calls == [[0, 1]]
        assert h.requested


# ---------------------------------------------------------------------------
# hapi Model.fit: ModelCheckpoint wiring + resume=True
# ---------------------------------------------------------------------------

def _hapi_setup(seed=3):
    pt.seed(seed)
    net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                           pt.nn.Linear(8, 1))
    model = pt.Model(net)
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=net.parameters())
    model.prepare(optimizer=opt, loss=pt.nn.MSELoss())
    rng = np.random.RandomState(0)
    data = [(rng.randn(4, 4).astype(np.float32),
             rng.randn(4, 1).astype(np.float32)) for _ in range(5)]
    return model, data


class TestHapiResume:
    def test_epoch_resume_matches_straight_run(self, tmp_path):
        from paddle_tpu.hapi.callbacks import ModelCheckpoint

        ref_model, data = _hapi_setup()
        ref = ref_model.fit(data, epochs=4, verbose=0)["loss"]

        model, data = _hapi_setup()
        cb = ModelCheckpoint(save_dir=str(tmp_path), save_freq=1,
                             keep_last_k=2)
        first = model.fit(data, epochs=2, verbose=0, callbacks=[cb])["loss"]

        model2, data = _hapi_setup(seed=99)  # different init — must not matter
        cb2 = ModelCheckpoint(save_dir=str(tmp_path))
        rest = model2.fit(data, epochs=4, verbose=0, callbacks=[cb2],
                          resume=True)["loss"]
        assert first == ref[:len(first)]
        assert rest == ref[len(first):]

    def test_mid_epoch_step_resume(self, tmp_path):
        """Preempt after batch 2 of epoch 0 (step-freq checkpoints);
        resume replays the remaining batches — losses match a straight
        2-epoch run exactly."""
        from paddle_tpu.hapi.callbacks import Callback, ModelCheckpoint

        ref_model, data = _hapi_setup()
        ref = ref_model.fit(data, epochs=2, verbose=0)["loss"]

        model, data = _hapi_setup()
        h = PreemptionHandler()  # not installed: driven programmatically

        class PreemptAt(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 2:
                    h.request()

        cb = ModelCheckpoint(save_dir=str(tmp_path), save_freq=1,
                             save_freq_unit="step", preemption_handler=h)
        # preempting callback runs BEFORE the checkpoint callback so the
        # request is visible at the same step boundary
        out = model.fit(data, epochs=2, verbose=0,
                        callbacks=[PreemptAt(), cb])["loss"]
        assert cb.preempted
        assert len(out) == 3  # stopped after batch index 2
        assert out == ref[:3]

        model2, data = _hapi_setup(seed=123)
        cb2 = ModelCheckpoint(save_dir=str(tmp_path), save_freq=1,
                              save_freq_unit="step")
        rest = model2.fit(data, epochs=2, verbose=0, callbacks=[cb2],
                          resume=True)["loss"]
        assert rest == ref[3:]

    def test_preemption_survives_epoch_unit_checkpointing(self, tmp_path):
        """With the DEFAULT epoch-unit checkpointing, a preemption save
        must not be displaced by the epoch-end save fit fires on the stop
        path — resume must continue mid-epoch, not skip to the next."""
        from paddle_tpu.hapi.callbacks import Callback, ModelCheckpoint

        ref_model, data = _hapi_setup()
        ref = ref_model.fit(data, epochs=2, verbose=0)["loss"]

        model, data = _hapi_setup()
        h = PreemptionHandler()

        class PreemptAt(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step == 1:
                    h.request()

        cb = ModelCheckpoint(save_dir=str(tmp_path), save_freq=1,
                             preemption_handler=h)  # epoch-unit default
        out = model.fit(data, epochs=2, verbose=0,
                        callbacks=[PreemptAt(), cb])["loss"]
        assert cb.preempted and len(out) == 2

        model2, data = _hapi_setup(seed=77)
        cb2 = ModelCheckpoint(save_dir=str(tmp_path))
        rest = model2.fit(data, epochs=2, verbose=0, callbacks=[cb2],
                          resume=True)["loss"]
        assert rest == ref[2:]

    def test_resume_with_no_checkpoint_is_cold_start(self, tmp_path):
        model, data = _hapi_setup()
        out = model.fit(data, epochs=1, verbose=0, save_dir=None,
                        resume=True)["loss"]
        assert len(out) == len(data)


# ---------------------------------------------------------------------------
# auto_parallel Engine: save/load through the manager
# ---------------------------------------------------------------------------

class TestEngineCheckpoint:
    def test_engine_checkpoint_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.auto_parallel import Engine

        pt.seed(5)
        net = pt.nn.Linear(4, 2)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
        eng = Engine(model=net, loss=pt.nn.MSELoss(), optimizer=opt)
        rng = np.random.RandomState(0)
        data = [(rng.randn(4, 4).astype(np.float32),
                 rng.randn(4, 2).astype(np.float32)) for _ in range(3)]
        eng.fit(data, epochs=1, verbose=0)
        eng.save_checkpoint(str(tmp_path), step=3, epoch=0, blocking=True)
        w = np.asarray(net.weight._value).copy()

        pt.seed(50)
        net2 = pt.nn.Linear(4, 2)
        opt2 = pt.optimizer.AdamW(learning_rate=1e-2,
                                  parameters=net2.parameters())
        eng2 = Engine(model=net2, loss=pt.nn.MSELoss(), optimizer=opt2)
        pos = eng2.load_checkpoint(str(tmp_path))
        assert pos == {"epoch": 0, "step": 3}
        np.testing.assert_array_equal(np.asarray(net2.weight._value), w)

    def test_engine_load_checkpoint_empty_dir(self, tmp_path):
        from paddle_tpu.distributed.auto_parallel import Engine

        net = pt.nn.Linear(2, 2)
        eng = Engine(model=net)
        assert eng.load_checkpoint(str(tmp_path)) is None
