"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod

from ..core.dtype import to_jax_dtype
from ..tensor import Tensor, to_tensor
from . import dispatch
from ._factory import ensure_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "meshgrid",
    "assign",
    "clone",
    "tril_indices",
    "triu_indices",
    "complex",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype="float32", name=None):
    return Tensor(jnp.zeros(_shape_list(shape), to_jax_dtype(dtype)))


def ones(shape, dtype="float32", name=None):
    return Tensor(jnp.ones(_shape_list(shape), to_jax_dtype(dtype)))


def full(shape, fill_value, dtype="float32", name=None):
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    return Tensor(jnp.full(_shape_list(shape), fv, to_jax_dtype(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    jd = to_jax_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jnp.zeros(x._value.shape, jd))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    jd = to_jax_dtype(dtype) if dtype is not None else x._value.dtype
    return Tensor(jnp.ones(x._value.shape, jd))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    jd = to_jax_dtype(dtype) if dtype is not None else x._value.dtype
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    return Tensor(jnp.full(x._value.shape, fv, jd))


def empty(shape, dtype="float32", name=None):
    # XLA has no uninitialized alloc; zeros is free under fusion.
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else "float32"
        )
    return Tensor(jnp.arange(start, end, step, to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype="float32", name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype="float32", name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=to_jax_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)

    return dispatch.apply(fn, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.diagflat(a, k=offset), x, op_name="diagflat")


def tril(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.tril(a, k=diagonal), x, op_name="tril")


def triu(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.triu(a, k=diagonal), x, op_name="triu")


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=to_jax_dtype(dtype)))


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    ts = [ensure_tensor(a) for a in args]
    outs = dispatch.apply(
        lambda *raws: tuple(jnp.meshgrid(*raws, indexing="ij")), *ts, op_name="meshgrid"
    )
    return list(outs)


def assign(x, output=None):
    """reference ops.yaml 'assign'."""
    if not isinstance(x, Tensor):
        x = to_tensor(np.asarray(x))
    out = dispatch.apply(lambda a: a + 0 if _dtype_mod.is_inexact_raw(a.dtype) else a, x, op_name="assign")
    if output is not None:
        output._set_value(out._value)
        return output
    return out


def clone(x, name=None):
    return ensure_tensor(x).clone()


def complex(real, imag, name=None):  # noqa: A001
    real, imag = ensure_tensor(real), ensure_tensor(imag)
    return dispatch.apply(jax.lax.complex, real, imag, op_name="complex")
