"""Mesh-native serving: ``dp`` replica engines x ``mp`` tensor-parallel
chips behind ONE placement scheduler.

``ShardedServingEngine`` is the cluster front end of the PR-14 scheduler
split (docs/serving.md "Sharded serving"):

- it builds one ``('mp',)`` submesh per ``dp`` replica over disjoint
  device rows (``distributed/serving_mesh.replica_meshes``), gives each
  replica its OWN model copy (weights column/row-parallel over ``mp``,
  replicated across replicas) and its own :class:`ServingEngine` — pool,
  slots, admission, fault containment, and the donated fused step all
  per replica, compiled ONCE per replica as an SPMD program;
- the paged KV pool inside each replica is sharded per-head
  (``[num_pages, H/mp, page_size, D]`` per chip), the ragged/paged
  kernels run per head shard under ``shard_map``, and the only hot-path
  cross-chip reduce is the row-parallel post-attention/post-MLP
  projection all-reduce GSPMD inserts;
- ``submit`` goes through the placement layer
  (``serving/placement.py``): least-loaded replica wins, queue-depth
  backpressure is the signal, and a typed ``Overloaded`` shed happens
  only when EVERY replica backpressures.

Scaling shape: aggregate decode slots and page-pool HBM grow linearly
with ``dp`` (each replica owns a full pool on its own chips); per-chip
pool bytes shrink ~1/mp.  Greedy serving stays token-for-token equal to
the single-chip engine and to ``generate()`` — the parity suite in
tests/test_sharded_serving.py pins it for (dp, mp) in
{(1,2), (2,1), (2,2)} on the forced-8-device CPU mesh.
"""
from __future__ import annotations

import itertools
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from ..distributed import serving_mesh as _srv_mesh
from ..telemetry import metrics as _tmetrics
from .engine import (
    Overloaded,
    Request,
    RequestState,
    ServingEngine,
    ServingError,
)
from .placement import LeastLoadedPlacement, PlacementScheduler

__all__ = ["ShardedServingEngine"]

_CLUSTER_SEQ = itertools.count()


class ShardedServingEngine:
    """``dp`` x ``mp`` sharded serving behind one submit/step interface.

    ``model`` becomes replica 0 (its parameters are committed to replica
    0's submesh — the engine takes placement ownership); further replicas
    are fresh instances loaded from its exact ``state_dict``
    (``model_factory`` overrides construction for classes whose
    ``__init__`` needs more than the config).  Engine knobs
    (``num_slots``, ``page_size``, pool sizing, fault containment, ...)
    pass through to every replica unchanged — they are per-replica
    quantities, so aggregate capacity is ``dp`` times each."""

    def __init__(self, model, *, dp: int = 1, mp: int = 1,
                 devices=None, model_factory: Optional[Callable] = None,
                 placement=None, engine_factory: Optional[Callable] = None,
                 **engine_kw):
        dp, mp = int(dp), int(mp)
        if mp > 1:
            # hard shard precondition, typed at construction (GL002
            # formatting) — not a shard_map crash deep in the first step
            _srv_mesh.validate_head_sharding(model.config.num_heads, mp)
        self.dp, self.mp = dp, mp
        self.meshes = _srv_mesh.replica_meshes(dp, mp, devices)
        self.replicas: List[ServingEngine] = []
        for i, mesh in enumerate(self.meshes):
            rm = model if i == 0 else _srv_mesh.clone_model(
                model, model_factory)
            _srv_mesh.shard_model_for_serving(rm, mesh)
            if engine_factory is not None:
                # replica-level composition hook: a speculative replica
                # (SpeculativeEngine + its own draft model clone) or a
                # LoRA-pooled replica (per-replica slab Tensors) —
                # docs/serving.md "Speculative decoding & multi-tenant
                # LoRA".  Signature: (model, mesh, index, **engine_kw).
                eng = engine_factory(rm, mesh, i, **engine_kw)
            else:
                eng = ServingEngine(rm, mesh=mesh, **engine_kw)
            self.replicas.append(eng)
        self.placement = PlacementScheduler(
            self.replicas, policy=placement or LeastLoadedPlacement())
        # per-tick replica stepping runs on one thread per replica (dp>1)
        # so the replicas' device work overlaps: each engine's step holds
        # only its own lock and drives only its own submesh, and the GIL
        # is released for the device execution + host fetch — strictly
        # sequential stepping would serialize the dp devices and break
        # the ~linear aggregate-tokens/s scaling on real hardware
        self._pool = (ThreadPoolExecutor(
            max_workers=dp, thread_name_prefix="sharded-serving-step")
            if dp > 1 else None)
        # -- elastic lifecycle (PR 19, docs/serving.md "Elasticity") ----
        # Each replica index is in exactly one state:
        #   active   — stepping, accepting new admissions
        #   draining — stepping (seated work must finish) but admission
        #              stopped; queued work already re-homed
        #   parked   — drained and NOT stepping (scale-down complete;
        #              its chips cost nothing until activate_replica)
        #   dead     — killed/closed; never comes back
        self._parked: set = set()
        self._dead: set = set()
        self._drain_deadline: Dict[int, Optional[float]] = {}
        # chip accounting for the elasticity win: one unit per replica
        # actually stepped per tick — chip-seconds ∝ replica_steps * mp
        self._replica_steps = 0
        # cluster-level fault hook (faults.py `replica_kill` fires at the
        # per-tick "cluster_step" point)
        self._fault_hook = None
        # brownout actuators (driven by serving/elastic.py, LIFO order)
        self.max_new_cap: Optional[int] = None   # rung 1: clamp admissions
        self.shedding = False                    # rung 4: refuse work
        self._orig_prefill_budget = [e.prefill_token_budget
                                     for e in self.replicas]
        label = {"cluster": str(next(_CLUSTER_SEQ))}
        self._cluster_label = label
        reg = _tmetrics.registry()
        self._rehomed_counter = reg.counter(
            "serving_rehomed_requests_total",
            "requests re-homed onto a survivor after a drain or replica "
            "loss").labels(**label)
        self._rehomed_synced = 0
        self._brownout_shed = reg.counter(
            "serving_brownout_shed_total",
            "requests refused at the brownout ladder's shed rung",
        ).labels(**label)

    # -- submission (placement layer) --------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, **kwargs) -> Request:
        """Place the request on the least-loaded replica and queue it
        there.  Typed ``Overloaded`` only when ALL replicas shed; the
        seated replica's index rides on ``request.replica``.

        Brownout rungs act here (elastic.py): rung 1 clamps ``max_new``
        for NEW admissions (seated requests keep their grant), the shed
        rung refuses work outright — both typed, both counted."""
        if self.shedding:
            self._brownout_shed.inc()
            raise Overloaded(
                "cluster browned out to shedding: offered load exceeds "
                "maximum degraded capacity — back off and retry")
        if self.max_new_cap is not None:
            max_new_tokens = min(int(max_new_tokens), self.max_new_cap)
        return self.placement.submit(prompt, max_new_tokens, **kwargs)

    # -- the serving loop --------------------------------------------------
    _IDLE_ROW = {"active_slots": 0, "queue_depth": 0, "pages_used": 0,
                 "pages_capacity": 0, "occupancy": 0.0,
                 "tokens_this_step": 0}

    def step(self) -> dict:
        """One cluster tick: every live replica runs its own fused step
        (its own admission, pool and fault containment), concurrently
        across replicas when dp > 1.  Returns aggregate step metrics plus
        the per-replica list (replica order preserved; parked/dead
        replicas contribute an all-zero placeholder row).

        Elastic upkeep rides the tick boundary: the ``cluster_step``
        fault hook may kill replicas first (their live work re-homes),
        drains whose replica emptied — or whose deadline passed — are
        finalized, and the placement layer's held re-home queue is swept
        (terminal requests reaped) and retried against freed capacity.
        """
        hook = self._fault_hook
        if hook is not None:
            ctx: dict = {"kill": []}
            hook("cluster_step", ctx)
            for i in ctx["kill"]:
                self.kill_replica(i)
        self._check_drains()
        return self._pooled_step()

    def _replica_step(self, i: int) -> dict:
        """One replica's work for this cluster tick — the subclass seam
        serving/disagg.py uses to run decode-role replicas for several
        sub-steps INSIDE the pooled barrier (their dispatches overlap
        the prefill replicas' longer steps instead of serializing after
        them)."""
        return self.replicas[i].step()

    def _pooled_step(self) -> dict:
        live = [i for i in range(len(self.replicas)) if self._stepping(i)]
        if self._pool is not None and len(live) > 1:
            stepped = dict(zip(live, self._pool.map(self._replica_step,
                                                    live)))
        else:
            stepped = {i: self._replica_step(i) for i in live}
        self._replica_steps += len(live)
        per = [stepped.get(i, dict(self._IDLE_ROW))
               for i in range(len(self.replicas))]
        self.placement.sweep()
        if self.placement.held:
            self.placement.flush_held()
        self._sync_rehomed()
        pages_used = sum(m["pages_used"] for m in per)
        pages_cap = sum(m["pages_capacity"] for m in per)
        agg = {
            "active_slots": sum(m["active_slots"] for m in per),
            "queue_depth": sum(m["queue_depth"] for m in per),
            "pages_used": pages_used,
            "pages_capacity": pages_cap,
            "occupancy": pages_used / pages_cap if pages_cap else 0.0,
            "replica_occupancy": [m["occupancy"] for m in per],
            "tokens_this_step": sum(m["tokens_this_step"] for m in per),
            "replicas": per,
        }
        return agg

    def run_until_idle(self, max_steps: Optional[int] = None) -> dict:
        """Step until every replica's queue and slots drain."""
        steps = 0
        while self.placement.pending():
            met = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if (not met["active_slots"] and not met["tokens_this_step"]
                    and self.placement.pending()):
                time.sleep(0.001)       # post-recovery backoff, any replica
        return self.metrics()

    def generate_batch(self, prompts, max_new_tokens: int = 32, *,
                       raise_on_failure: bool = True,
                       **kwargs) -> List[np.ndarray]:
        """Submit every prompt through placement, drain the cluster,
        return prompt+generated ids in submission order (the single-engine
        ``generate_batch`` contract, including the typed error on non-DONE
        terminals)."""
        reqs = [self.submit(p, max_new_tokens, **kwargs) for p in prompts]
        self.run_until_idle()
        bad = [r for r in reqs if r.state != RequestState.DONE]
        if bad and raise_on_failure:
            detail = ", ".join(f"request {r.id}: {r.state}" for r in bad)
            raise ServingError(
                f"generate_batch: {len(bad)}/{len(reqs)} request(s) did "
                f"not complete ({detail})") from bad[0].error
        return [r.output_ids() for r in reqs]

    # -- elastic replica lifecycle (PR 19) ---------------------------------
    def _stepping(self, i: int) -> bool:
        """Does replica ``i`` burn a replica-step this tick?  Active and
        draining replicas do (seated work must run to completion);
        parked and dead ones don't — that difference IS the chip-seconds
        saving the chaos trace measures."""
        return i not in self._dead and i not in self._parked

    @property
    def active_dp(self) -> int:
        """Replicas currently stepping (active + draining)."""
        return sum(1 for i in range(len(self.replicas))
                   if self._stepping(i))

    def replica_states(self) -> List[str]:
        out = []
        for i, e in enumerate(self.replicas):
            if i in self._dead:
                out.append("dead")
            elif i in self._parked:
                out.append("parked")
            elif getattr(e, "draining", False):
                out.append("draining")
            else:
                out.append("active")
        return out

    def _rehome(self, reqs: List[Request]) -> int:
        """Re-seat harvested live requests on survivors via the placement
        walk; the unseatable remainder parks in ``placement.held`` (still
        live) and is retried every tick.  Returns requests seated now."""
        seated = sum(1 for r in reqs if self.placement.resubmit(r))
        self.placement.sweep()
        self._sync_rehomed()
        return seated

    def _sync_rehomed(self):
        cur = self.placement.rehomed_total
        if cur > self._rehomed_synced:
            self._rehomed_counter.inc(cur - self._rehomed_synced)
            self._rehomed_synced = cur

    def begin_drain_replica(self, i: int,
                            deadline_s: Optional[float] = None) -> int:
        """Start draining replica ``i``: admission stops immediately, its
        queued requests re-home via placement NOW, and its seated
        requests keep running.  With a ``deadline_s``, seated work still
        unfinished when it expires is checkpointed (token-prefix + RNG
        state folded into the request) and re-homed too; without one the
        drain completes whenever the last seated request finishes.
        Returns the number of queued requests harvested."""
        if i in self._dead:
            raise ServingError(f"replica {i} is dead; cannot drain")
        queued = self.replicas[i].begin_drain()
        self._drain_deadline[i] = (None if deadline_s is None
                                   else time.monotonic() + deadline_s)
        self._rehome(queued)
        return len(queued)

    def _check_drains(self, now: Optional[float] = None):
        for i in list(self._drain_deadline):
            e = self.replicas[i]
            deadline = self._drain_deadline[i]
            if e.drained:
                self.finish_drain_replica(i)
            elif deadline is not None and (
                    now if now is not None else time.monotonic()
            ) >= deadline:
                # deadline: fold the stragglers and re-home them — the
                # drained replica parks THIS tick, not eventually
                self._rehome(e.checkpoint_seated())
                self.finish_drain_replica(i)

    def finish_drain_replica(self, i: int):
        """Park a drained replica: it stops stepping (chip-seconds stop
        accruing) but keeps its pool — ``activate_replica`` brings it
        back without recompilation or weight reload."""
        self._drain_deadline.pop(i, None)
        self._parked.add(i)

    def drain_replica(self, i: int, *,
                      deadline_s: Optional[float] = None,
                      max_steps: int = 500) -> int:
        """Synchronous convenience: begin the drain and step the cluster
        until replica ``i`` parks (tests and the smoke case).  Seated
        work elsewhere advances normally during the wait."""
        harvested = self.begin_drain_replica(i, deadline_s=deadline_s)
        steps = 0
        while i not in self._parked:
            self.step()
            steps += 1
            if steps >= max_steps:
                raise ServingError(
                    f"replica {i} failed to drain within {max_steps} "
                    "cluster steps")
        return harvested

    def activate_replica(self, i: int):
        """Scale-up: bring a parked (or mid-drain) replica back to
        active.  Its pool, program and weights never left, so the only
        cost is the placement layer seeing it eligible again."""
        if i in self._dead:
            raise ServingError(f"replica {i} is dead; cannot activate")
        self._parked.discard(i)
        self._drain_deadline.pop(i, None)
        self.replicas[i].resume_admission()

    def kill_replica(self, i: int) -> int:
        """Replica loss (fault path, `replica_kill`): close replica ``i``
        NOW and re-home its live work onto survivors — queued requests
        re-route directly, seated ones are checkpointed off the host
        mirrors (tokens emitted so far live host-side, so a chip loss
        does not lose them).  Requests no survivor can seat park in the
        held queue; they only go FAILED when no eligible replica remains
        (placement.sweep).  Returns the number of live requests
        harvested."""
        if i in self._dead:
            return 0
        e = self.replicas[i]
        self._dead.add(i)
        self._parked.discard(i)
        self._drain_deadline.pop(i, None)
        live = e.begin_drain()          # stops admission + harvests queue
        live += e.checkpoint_seated()
        e.close()
        self._rehome(live)
        return len(live)

    # -- brownout actuators (elastic.py drives these, LIFO on recovery) ----
    def set_max_new_cap(self, cap: Optional[int]):
        """Rung 1: clamp ``max_new_tokens`` for NEW admissions (None
        restores).  Seated requests keep their original grant."""
        self.max_new_cap = None if cap is None else max(1, int(cap))

    def set_speculation(self, enabled: bool) -> int:
        """Rung 2: toggle speculative decoding on every replica that has
        it (SpeculativeEngine.speculation_enabled).  Returns how many
        replicas were toggled — 0 means the rung is a no-op here."""
        n = 0
        for idx, e in enumerate(self.replicas):
            if idx in self._dead:
                continue
            if hasattr(e, "speculation_enabled"):
                e.speculation_enabled = bool(enabled)
                n += 1
        return n

    def shrink_prefill_budget(self, frac: float = 0.5):
        """Rung 3: shrink every replica's per-step prefill token budget.
        Shrinking is retrace-free (plans stay within the compiled
        ``t_max`` geometry); growing past the construction-time budget
        would overflow it, so restore only ever returns to the original."""
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"frac={frac} must be in (0, 1]")
        for idx, e in enumerate(self.replicas):
            if idx in self._dead:
                continue
            e.prefill_token_budget = max(
                1, int(self._orig_prefill_budget[idx] * frac))

    def restore_prefill_budget(self):
        for idx, e in enumerate(self.replicas):
            if idx in self._dead:
                continue
            e.prefill_token_budget = self._orig_prefill_budget[idx]

    def set_shedding(self, on: bool):
        """Rung 4 (last resort): refuse new work with typed Overloaded."""
        self.shedding = bool(on)

    # -- observability -----------------------------------------------------
    def metrics(self) -> dict:
        """Cluster metrics: summed counters/capacities (aggregate slots
        and page HBM scale linearly with ``dp`` — the acceptance
        criterion), per-chip pool bytes (shrink ~1/mp), and the full
        per-replica metrics list."""
        per = [eng.metrics() for eng in self.replicas]
        sum_keys = ("steps", "tokens", "admitted", "completed",
                    "fused_steps", "prefill_tokens", "failed", "cancelled",
                    "timed_out", "shed", "quarantined", "recoveries",
                    "rebuilds", "pages_used", "pages_capacity",
                    "active_slots", "queue_depth", "cache_bytes",
                    "work_items", "work_capacity", "block_rows",
                    "block_row_capacity", "padded_rows", "padded_flops",
                    # per-replica prefix caches (docs/serving.md "Prefix
                    # cache"): hits/misses sum exactly; hit RATE is
                    # re-derived from the sums below
                    "prefix_hits", "prefix_partial_hits", "prefix_misses",
                    "prefix_evictions", "prefix_cached_tokens",
                    "prefix_cache_pages", "prefix_cache_nodes",
                    "shared_pages",
                    # disaggregated hand-off (serving/disagg.py): both
                    # sides of every committed PageTransfer — equal sums
                    # cluster-wide when every transfer commits
                    "transferred_out", "transferred_in")
        out = {k: sum(int(m.get(k, 0)) for m in per) for k in sum_keys}
        looked = (out["prefix_hits"] + out["prefix_partial_hits"]
                  + out["prefix_misses"])
        out["prefix_hit_rate"] = ((out["prefix_hits"]
                                   + out["prefix_partial_hits"]) / looked
                                  if looked else 0.0)
        # cluster-level sheds (all replicas backpressured) on top of the
        # replicas' own shed counters (queue-wait shedding etc.) — the
        # placement layer skips full replicas instead of probing their
        # submit, so one rejected request counts exactly once
        out["shed"] += self.placement.shed_total
        out["placement_shed"] = self.placement.shed_total
        out["dp"] = self.dp
        out["mp"] = self.mp
        out["slot_capacity"] = sum(e.num_slots for e in self.replicas)
        out["cache_bytes_per_chip"] = (per[0]["cache_bytes_per_chip"]
                                       if per else 0)
        out["routed"] = list(self.placement.routed)
        # elastic lifecycle observability (PR 19)
        out["replica_states"] = self.replica_states()
        out["active_dp"] = self.active_dp
        out["replica_steps"] = self._replica_steps
        # chip-seconds proxy: every stepped replica burns its mp chips
        # for one tick — the quantity the chaos trace minimizes
        out["replica_step_chip_ticks"] = self._replica_steps * self.mp
        out["rehomed"] = self.placement.rehomed_total
        out["held"] = len(self.placement.held)
        out["brownout_shed"] = int(self._brownout_shed.value)
        out["shed"] += out["brownout_shed"]
        out["per_replica"] = per
        return out

    @property
    def compiled_programs(self) -> int:
        return sum(e.compiled_programs for e in self.replicas)

    def lint_reports(self):
        return [r for e in self.replicas for r in e.lint_reports()]

    def close(self):
        for eng in self.replicas:
            eng.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        # same hygiene as the engine: recycled clusters must not grow
        # the Prometheus exposition forever (handles keep working)
        _tmetrics.registry().drop_labels(**self._cluster_label)
