"""AST dy2static: NATIVE python if/while over traced tensors compile.

Reference: python/paddle/jit/dy2static/ast_transformer.py + the BERT
dygraph_to_static fixture (test/dygraph_to_static/test_bert.py) — the
acceptance bar is compiled == eager with UNMODIFIED model code."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.jit.dy2static import Dy2StaticUnsupported, set_default_max_iter


def test_native_if_bert_style_branch():
    """The round-3 BERT fixture, with static_nn.cond replaced by a NATIVE
    python if — the dy2static AST pass must functionalize it."""

    class TinyBertWithBranch(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            pt.seed(11)
            self.emb = pt.nn.Embedding(64, 16)
            self.fc = pt.nn.Linear(16, 16)
            self.head = pt.nn.Linear(16, 2)

        def forward(self, ids):
            h = self.emb(ids)
            h = pt.ops.mean(h, axis=1)
            if pt.ops.mean(h) > 0.0:
                h = pt.nn.functional.gelu(self.fc(h))
            else:
                h = pt.nn.functional.relu(self.fc(h)) * 0.5
            return self.head(h)

    model = TinyBertWithBranch()
    ids = pt.to_tensor(np.random.RandomState(0).randint(0, 64, (4, 8)),
                       dtype="int64")
    eager = model(ids).numpy()
    compiled_fwd = pt.jit.to_static(model.forward)
    for _ in range(3):
        np.testing.assert_allclose(compiled_fwd(ids).numpy(), eager,
                                   rtol=1e-5, atol=1e-6)


def test_native_if_read_then_assign():
    def fn(x):
        y = x * 2.0
        if pt.ops.sum(x) > 0.0:
            y = y + 1.0  # read-then-assign of an enclosing local
        return pt.ops.sum(y)

    compiled = pt.jit.to_static(fn)
    xp = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    xn = pt.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(float(compiled(xp)), float(fn(xp)), rtol=1e-6)
    np.testing.assert_allclose(float(compiled(xn)), float(fn(xn)), rtol=1e-6)


def test_native_if_both_branches_return():
    def fn(x):
        if pt.ops.sum(x) > 0.0:
            return x * 2.0
        else:
            return x - 1.0

    compiled = pt.jit.to_static(fn)
    xp = pt.to_tensor(np.array([3.0], np.float32))
    xn = pt.to_tensor(np.array([-3.0], np.float32))
    np.testing.assert_allclose(compiled(xp).numpy(), fn(xp).numpy())
    np.testing.assert_allclose(compiled(xn).numpy(), fn(xn).numpy())


def test_native_elif_chain():
    def fn(x):
        s = pt.ops.sum(x)
        if s > 10.0:
            out = x * 3.0
        elif s > 0.0:
            out = x * 2.0
        else:
            out = x * -1.0
        return pt.ops.sum(out)

    compiled = pt.jit.to_static(fn)
    for arr in ([20.0], [1.0], [-5.0]):
        x = pt.to_tensor(np.array(arr, np.float32))
        np.testing.assert_allclose(float(compiled(x)), float(fn(x)),
                                   rtol=1e-6)


def test_native_while_accumulates():
    def fn(x):
        i = pt.to_tensor(0)
        with pt.no_grad():
            while i < 4:
                x = x * 2.0
                i = i + 1
        return pt.ops.sum(x)

    compiled = pt.jit.to_static(fn)
    x = pt.to_tensor(np.array([1.5], np.float32))
    np.testing.assert_allclose(float(compiled(x)), 1.5 * 16, rtol=1e-6)
    np.testing.assert_allclose(float(compiled(x)), 1.5 * 16, rtol=1e-6)


def test_native_while_differentiable_with_max_iter():
    set_default_max_iter(8)
    try:
        def fn(x):
            i = pt.to_tensor(0)
            while i < 3:
                x = x * 2.0
                i = i + 1
            loss = pt.ops.sum(x)
            loss.backward()
            return loss, x.grad

        compiled = pt.jit.to_static(fn)
        x = pt.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
        loss, _ = compiled(x)
        np.testing.assert_allclose(float(loss), 8.0, rtol=1e-6)
    finally:
        set_default_max_iter(None)


def test_python_predicates_untouched():
    """if/while over plain python values keep exact python semantics
    (side effects, break) — no tensor machinery involved."""
    log = []

    def fn(x, flag):
        if flag:  # python bool
            log.append("taken")
            x = x + 1.0
        n = 0
        while n < 3:
            if n == 1:
                n += 2
                continue
            n += 1
        return x * float(n)

    compiled = pt.jit.to_static(fn)
    x = pt.to_tensor(np.array([1.0], np.float32))
    out = compiled(x, True)
    assert log  # python side effect ran
    np.testing.assert_allclose(out.numpy(), [6.0], rtol=1e-6)


def test_unsupported_pattern_names_source_line():
    """break inside a tensor-predicate while: eager (undecorated) python
    semantics are untouched; to_static raises a clear error naming the
    source line on the FIRST call (the reference dy2static also errors at
    conversion, not after N eager calls)."""

    def fn(x):
        i = pt.to_tensor(0)
        while i < 5:
            if int(i) == 2:  # host read: cannot trace
                break
            i = i + 1
        return x

    x = pt.to_tensor(np.array([1.0], np.float32))
    np.testing.assert_allclose(fn(x).numpy(), [1.0])  # eager untouched

    def traced_bad(x):
        s = pt.ops.sum(x)
        while s > 0.0:
            if True:
                break
            s = s - 1.0
        return x

    compiled = pt.jit.to_static(traced_bad)
    with pytest.raises((Dy2StaticUnsupported, RuntimeError)) as ei:
        compiled(x)
    assert "line" in str(ei.value) or "control flow" in str(ei.value)


def test_native_for_traced_range_bound():
    """Round-5 verdict item 4: `for i in range(n_t)` over a TRACED bound
    must compile into the bounded-while machinery (not bake in the
    scouted trip count) and match eager."""
    @pt.jit.to_static
    def fn(n_t, x):
        acc = x * 0.0
        for i in range(n_t):
            acc = acc + x * pt.ops.cast(i, "float32")
        return acc

    x = pt.to_tensor(np.ones((3,), np.float32))
    np.testing.assert_allclose(fn(pt.to_tensor(4), x).numpy(), 6.0)
    # SAME compiled callable, different runtime bound: the trip count is
    # a traced value, not a baked constant
    np.testing.assert_allclose(fn(pt.to_tensor(6), x).numpy(), 15.0)


def test_native_for_start_step_and_python_bounds():
    @pt.jit.to_static
    def fn(n_t, x):
        s = x * 0.0
        for i in range(1, n_t, 2):
            s = s + pt.ops.cast(i, "float32")
        return s

    x = pt.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(fn(pt.to_tensor(8), x).numpy(), 16.0)

    @pt.jit.to_static
    def py(x):
        acc = x * 0.0
        for i in range(3):
            acc = acc + x
        return acc

    np.testing.assert_allclose(py(x).numpy(), 3.0)


def test_native_for_over_tensor_iterable():
    @pt.jit.to_static
    def fn(xs):
        s = pt.to_tensor(0.0)
        for row in xs:
            s = s + pt.ops.sum(row)
        return s

    xs = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
    assert abs(float(fn(xs)) - 15.0) < 1e-6


def test_native_for_unsupported_break_names_line():
    @pt.jit.to_static
    def fn(n_t):
        s = pt.to_tensor(0.0)
        for i in range(n_t):
            if i > pt.to_tensor(100):
                break
            s = s + 1.0
        return s

    with pytest.raises((Dy2StaticUnsupported, RuntimeError)) as ei:
        fn(pt.to_tensor(3))
    msg = str(ei.value)
    assert "line" in msg and ("break" in msg or "control flow" in msg)


def test_native_for_zero_trip_preserves_target():
    """Python leaves the loop variable untouched when the range is
    empty; the traced rewrite must too (round-5 review finding)."""
    @pt.jit.to_static
    def fn(n_t, x):
        i = pt.to_tensor(100.0)
        acc = x * 0.0
        for i in range(n_t):
            acc = acc + x
        return acc + pt.ops.cast(i, "float32")

    x = pt.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(fn(pt.to_tensor(0), x).numpy(), 100.0)
    np.testing.assert_allclose(fn(pt.to_tensor(3), x).numpy(), 3.0 + 2.0)


def test_native_for_shadowed_range_untouched():
    def range(n):  # noqa: A001 - deliberate shadow
        return [10, 20]

    @pt.jit.to_static
    def fn(x):
        s = x * 0.0
        for i in range(2):
            s = s + float(i)
        return s

    x = pt.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(fn(x).numpy(), 30.0)


def test_native_for_zero_step_raises_like_python():
    @pt.jit.to_static
    def fn(n_t):
        s = pt.to_tensor(0.0)
        for i in range(0, n_t, 0):
            s = s + 1.0
        return s

    with pytest.raises(ValueError):
        fn(pt.to_tensor(3))
