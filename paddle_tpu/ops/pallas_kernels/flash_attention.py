"""Flash attention on TPU — an owned Pallas kernel (fwd + bwd).

Reference analog: paddle/phi/kernels/gpu/flash_attn_kernel.cu (which
dynloads third_party/flashattn).  On TPU the memory-hierarchy-aware
attention kernel is a Pallas/Mosaic program written here from scratch:

- forward: online-softmax accumulation over KV blocks (running max m,
  running denominator l, f32 accumulator), causal blocks skipped at the
  grid level with ``pl.when``; saves per-row logsumexp for backward.
- backward: two kernels — one accumulating dK/dV per KV block over Q
  blocks, one accumulating dQ per Q block over KV blocks — both
  recomputing the probability matrix from (q, k, lse) instead of saving
  the [S, S] attention matrix, which is the whole point of flash
  attention.  ``delta = rowsum(dO * O)`` is precomputed in XLA.

All index maps use plain int arithmetic (no lax.select), so the kernel
traces cleanly whether or not the framework's int64 (x64) mode is on —
the shipped jax kernel does not.

Layouts: ``flash_attention_bnsd`` takes [B, N, S, D] (head-major);
``flash_attention_bshd`` adapts [B, S, N, D].  CPU falls back to the
numerically-identical XLA expression (pallas interpret mode is too slow
for tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core import flags as _flags
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import numpy as np


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def shape_unsupported_reason(seq_len: int, head_dim: int):
    """``None`` when the kernel accepts the shape, else the structured
    GL002-coded :class:`analysis.codes.GateReason` it falls back for —
    the SAME rule and formatting the graph linter reports, so a kernel
    fallback and a lint finding describe one hazard identically."""
    from ...analysis.codes import flash_gate_reason

    return flash_gate_reason(seq_len, head_dim)


def shape_supported(seq_len: int, head_dim: int) -> bool:
    """The ONE eligibility gate for this kernel (kept here so callers —
    nn/functional/attention.py and the stacked GPT block — can't drift):
    seqlen divisible by the 128-multiple blocks, head dim a 64 multiple
    (validated on TPU at d=64 and d=128).  On TPU hosts an ineligible
    shape is reported once per shape with its GL002 reason instead of
    silently taking the slower XLA expression."""
    reason = shape_unsupported_reason(seq_len, head_dim)
    if reason is not None and _on_tpu():
        from ...analysis.codes import note_fallback

        note_fallback(reason)
    return reason is None


NEG_INF = np.float32(-1e30)


def _dot(a, b, dims):
    """MXU dot with fp32 accumulation, precision picked per operand dtype.

    For sub-fp32 operands (bf16/fp16 under AMP) precision MUST be DEFAULT:
    the package sets jax_default_matmul_precision="highest" globally (fp32
    OpTest parity), and under "highest" Mosaic receives
    contract_precision<fp32> for bf16 operands and rejects the kernel with
    "Bad lhs type".  The accumulator is fp32 via preferred_element_type, so
    DEFAULT loses nothing there.  For fp32 operands, DEFAULT would let the
    MXU round inputs through bf16 passes — select HIGHEST so an fp32 call
    keeps full fp32 contraction (ADVICE round 5)."""
    fp32 = (jnp.dtype(a.dtype) == jnp.float32
            and jnp.dtype(b.dtype) == jnp.float32)
    return jax.lax.dot_general(
        a, b, (dims, ((), ())),
        precision=(jax.lax.Precision.HIGHEST if fp32
                   else jax.lax.Precision.DEFAULT),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
                *, scale, causal, block_q, block_kv, n_kv):
    kv_i = pl.program_id(2)
    q_i = pl.program_id(1)

    @pl.when(kv_i == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    # causal: a KV block strictly above the diagonal contributes nothing
    run = True
    if causal:
        run = kv_i * block_kv <= q_i * block_q + (block_q - 1)

    @pl.when(run)
    def _body():
        # MXU discipline: dots take the STORAGE dtype (bf16 under AMP —
        # the native MXU input width) and accumulate in fp32 via
        # preferred_element_type; only the softmax runs in fp32 on the
        # VPU.  Casting operands up to fp32 here would push the matmuls
        # off the fast bf16 MXU path for zero accuracy gain (accumulation
        # is fp32 either way).
        q = q_ref[0]                                # [block_q, D]
        k = k_ref[0]                                # [block_kv, D]
        v = v_ref[0]
        s = _dot(q, k, ((1,), (1,))) * np.float32(scale)
        if causal:
            rows = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_i * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_sc[:, :1]                        # [block_q, 1]
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [block_q, block_kv]
        l_cur = jnp.sum(p, axis=-1, keepdims=True)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + l_cur
        acc_sc[...] = acc_sc[...] * alpha + _dot(p.astype(v.dtype), v, ((1,), (0,)))
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(kv_i == n_kv - 1)
    def _finish():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, np.float32(1.0), l)
        o_ref[0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        # [block_q, 1] -> [1, block_q] -> sublane-broadcast [8, block_q]
        # (TPU block shapes need the 2nd-minor dim to be a multiple of 8)
        lse = jnp.transpose(m_sc[:, :1] + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape).astype(jnp.float32)


def _flash_fwd(q, k, v, scale, causal, block_q, block_kv):
    bn, s, d = q.shape
    n_q = s // block_q
    n_kv = s // block_kv
    grid = (bn, n_q, n_kv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_kv=block_kv, n_kv=n_kv)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, np.int32(0))),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, np.int32(0))),
            pl.BlockSpec((1, 8, block_q), lambda b, qi, ki: (b, np.int32(0), qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, s, d), q.dtype),
            jax.ShapeDtypeStruct((bn, 8, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v)
    return out, lse[:, 0, :]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc,
                    *, scale, causal, block_q, block_kv, n_q):
    kv_i = pl.program_id(1)
    q_i = pl.program_id(2)

    @pl.when(q_i == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    run = True
    if causal:
        # a Q block strictly above this KV block never attends to it
        run = q_i * block_q + (block_q - 1) >= kv_i * block_kv

    @pl.when(run)
    def _body():
        # same MXU discipline as the fwd kernel: operands in storage
        # dtype, fp32 accumulation; fp32 only for softmax/dS on the VPU
        q = q_ref[0]                                 # [block_q, D]
        k = k_ref[0]                                 # [block_kv, D]
        v = v_ref[0]
        do = do_ref[0]                               # [block_q, D]
        lse = jnp.transpose(lse_ref[0][:1, :])       # [block_q, 1]
        delta = jnp.transpose(delta_ref[0][:1, :])   # [block_q, 1]
        s = _dot(q, k, ((1,), (1,))) * np.float32(scale)
        if causal:
            rows = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_i * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                         # [block_q, block_kv]
        # dV += P^T dO
        dv_sc[...] += _dot(p.astype(do.dtype), do, ((0,), (0,)))
        # dP = dO V^T ; dS = P * (dP - delta)
        dp = _dot(do, v, ((1,), (1,)))
        ds = p * (dp - delta)
        # dK += dS^T Q * scale
        dk_sc[...] += np.float32(scale) * _dot(ds.astype(q.dtype), q, ((0,), (0,)))

    @pl.when(q_i == n_q - 1)
    def _finish():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc,
                   *, scale, causal, block_q, block_kv, n_kv):
    q_i = pl.program_id(1)
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    run = True
    if causal:
        run = kv_i * block_kv <= q_i * block_q + (block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = jnp.transpose(lse_ref[0][:1, :])       # [block_q, 1]
        delta = jnp.transpose(delta_ref[0][:1, :])   # [block_q, 1]
        s = _dot(q, k, ((1,), (1,))) * np.float32(scale)
        if causal:
            rows = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_i * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        ds = p * (dp - delta)
        dq_sc[...] += np.float32(scale) * _dot(ds.astype(k.dtype), k, ((1,), (0,)))

    @pl.when(kv_i == n_kv - 1)
    def _finish():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, causal, block_q, block_kv):
    bn, s, d = q.shape
    n_q = s // block_q
    n_kv = s // block_kv
    # delta_i = rowsum(dO_i * O_i): cheap elementwise+reduce, done in XLA
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # sublane-broadcast [bn, s] -> [bn, 8, s] for legal TPU block shapes
    lse = jnp.broadcast_to(lse[:, None, :], (bn, 8, s))
    delta = jnp.broadcast_to(delta[:, None, :], (bn, 8, s))

    dkv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, n_q=n_q),
        grid=(bn, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, ki, qi: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, ki, qi: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, np.int32(0))),
            pl.BlockSpec((1, 8, block_q), lambda b, ki, qi: (b, np.int32(0), qi)),
            pl.BlockSpec((1, 8, block_q), lambda b, ki, qi: (b, np.int32(0), qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, d), lambda b, ki, qi: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, ki, qi: (b, ki, np.int32(0))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, s, d), q.dtype),
            jax.ShapeDtypeStruct((bn, s, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv, n_kv=n_kv),
        grid=(bn, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_kv, d), lambda b, qi, ki: (b, ki, np.int32(0))),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, np.int32(0))),
            pl.BlockSpec((1, 8, block_q), lambda b, qi, ki: (b, np.int32(0), qi)),
            pl.BlockSpec((1, 8, block_q), lambda b, qi, ki: (b, np.int32(0), qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, np.int32(0))),
        out_shape=jax.ShapeDtypeStruct((bn, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(q, k, v, do, lse, delta)

    return dq, dkv[0], dkv[1]


# ---------------------------------------------------------------------------
# custom-vjp wrapper (head-major [B, N, S, D])
# ---------------------------------------------------------------------------

_flags.define_flag("FLAGS_flash_block_q", 0,
                   "flash-attention q block size override (0 = auto)")
_flags.define_flag("FLAGS_flash_block_kv", 0,
                   "flash-attention kv block size override (0 = auto)")


def _auto_block(s: int) -> int:
    from ...analysis.codes import default_block

    return default_block(s)


def _pick_blocks(s: int, d: int = 0, dtype=None):
    """Block sizes for one (seq, head_dim, dtype) specialization, in
    priority order: explicit FLAGS_flash_block_q / FLAGS_flash_block_kv
    overrides (a user pin beats the tuner, per side), then the autotune
    table (``analysis/autotune.py`` — a measured or seeded entry for this
    exact shape key; requires ``d``), then the historical ``_auto_block``
    default.  Invalid flag overrides (non-positive, non-divisor) fall
    back down the chain for that side only."""
    def override(name):
        try:
            v = int(_flags.flag(name) or 0)
        except (TypeError, ValueError):
            return None
        if v > 0:
            v = min(v, s)
            if s % v == 0:
                return v
        return None

    fq = override("FLAGS_flash_block_q")
    fkv = override("FLAGS_flash_block_kv")
    tuned = None
    if d and (fq is None or fkv is None):
        from ...analysis import autotune as _autotune

        tuned = _autotune.kernel_params(
            "flash_attention", {"seq": s, "head_dim": d}, dtype)
        if tuned:
            tbq = int(tuned.get("block_q") or 0)
            tbkv = int(tuned.get("block_kv") or 0)
            if tbq <= 0 or tbkv <= 0 or s % tbq or s % tbkv:
                tuned = None  # forced/tampered/partial params that
                #               cannot tile s — fall back whole
    bq = fq or (tuned and int(tuned["block_q"])) or _auto_block(s)
    bkv = fkv or (tuned and int(tuned["block_kv"])) or _auto_block(s)
    return bq, bkv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bnsd(q, k, v, causal, scale):
    out, _ = _flash_bnsd_fwd(q, k, v, causal, scale)
    return out


def _flash_bnsd_fwd(q, k, v, causal, scale):
    b, n, s, d = q.shape
    bq, bkv = _pick_blocks(s, d, q.dtype)
    fq, fk, fv = (t.reshape(b * n, s, d) for t in (q, k, v))
    out, lse = _flash_fwd(fq, fk, fv, scale, causal, bq, bkv)
    return out.reshape(b, n, s, d), (q, k, v, out.reshape(b, n, s, d), lse)


def _flash_bnsd_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    b, n, s, d = q.shape
    bq, bkv = _pick_blocks(s, d, q.dtype)
    dq, dk, dv = _flash_bwd(
        q.reshape(b * n, s, d), k.reshape(b * n, s, d), v.reshape(b * n, s, d),
        out.reshape(b * n, s, d), lse, g.reshape(b * n, s, d),
        scale, causal, bq, bkv)
    return (dq.reshape(b, n, s, d), dk.reshape(b, n, s, d),
            dv.reshape(b, n, s, d))


_flash_bnsd.defvjp(_flash_bnsd_fwd, _flash_bnsd_bwd)


def flash_attention_bnsd(q, k, v, *, causal: bool = False, sm_scale=None):
    """q/k/v: [B, N, S, D] -> [B, N, S, D] (head-major layout)."""
    scale = float(sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5))
    if _on_tpu():
        return _flash_bnsd(q, k, v, causal, scale)
    return _xla_reference_bnsd(q, k, v, causal, scale)


def _xla_reference_bnsd(qh, kh, vh, causal, scale):
    s = jnp.einsum("bnqd,bnkd->bnqk", qh, kh,
                   preferred_element_type=jnp.float32) * np.float32(scale)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p.astype(qh.dtype), vh)


def flash_attention_bshd(q, k, v, *, causal: bool = False):
    """q/k/v: [B, S, N, D] -> [B, S, N, D]."""
    scale = float(1.0 / (q.shape[-1] ** 0.5))
    qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))  # [B,N,S,D]
    if _on_tpu():
        out = _flash_bnsd(qh, kh, vh, causal, scale)
    else:
        out = _xla_reference_bnsd(qh, kh, vh, causal, scale)
    return jnp.swapaxes(out, 1, 2)
