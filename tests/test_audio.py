"""audio features + IO (reference: python/paddle/audio/features/layers.py,
backends/wave_backend.py). librosa-style numeric sanity on synthetic
signals."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.audio import backends, features, functional as AF


def _sine(sr=8000, f=440.0, secs=0.25):
    t = np.arange(int(sr * secs)) / sr
    return np.sin(2 * np.pi * f * t).astype(np.float32)


def test_get_window_shapes():
    for name in ("hann", "hamming", "blackman", "bartlett"):
        w = AF.get_window(name, 64)
        assert w.shape == [64]
        assert float(w.numpy().min()) >= -1e-6 and float(w.numpy().max()) <= 1.0001


def test_mel_hz_roundtrip():
    hz = 440.0
    mel = AF.hz_to_mel(hz)
    back = AF.mel_to_hz(mel)
    np.testing.assert_allclose(back, hz, rtol=1e-4)


def test_fbank_matrix_shape_and_rows():
    fb = AF.compute_fbank_matrix(sr=8000, n_fft=256, n_mels=20)
    assert fb.shape == [20, 129]
    assert float(fb.numpy().min()) >= 0.0


def test_spectrogram_peak_at_tone():
    sr, f = 8000, 1000.0
    sig = pt.to_tensor(_sine(sr, f)[None, :])
    spec = features.Spectrogram(n_fft=256, hop_length=128)(sig)
    mag = spec.numpy()[0]  # [freq, time]
    peak_bin = mag.mean(axis=1).argmax()
    expect_bin = round(f / (sr / 256))
    assert abs(int(peak_bin) - expect_bin) <= 1


def test_mfcc_pipeline_shapes():
    sr = 8000
    sig = pt.to_tensor(_sine(sr)[None, :])
    mfcc = features.MFCC(sr=sr, n_mfcc=13, n_fft=256, n_mels=24,
                         f_max=sr / 2)(sig)
    assert mfcc.shape[0] == 1 and mfcc.shape[1] == 13
    assert np.isfinite(mfcc.numpy()).all()


def test_logmel_finite():
    sr = 8000
    sig = pt.to_tensor(_sine(sr)[None, :])
    lm = features.LogMelSpectrogram(sr=sr, n_fft=256, n_mels=24,
                                    f_max=sr / 2, top_db=80.0)(sig)
    assert np.isfinite(lm.numpy()).all()


def test_wav_roundtrip(tmp_path):
    sr = 8000
    sig = _sine(sr)
    path = str(tmp_path / "t.wav")
    backends.save(path, pt.to_tensor(sig[None, :]), sr)
    loaded, sr2 = backends.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(loaded.numpy()[0], sig, atol=2e-4)
    meta = backends.info(path)
    assert meta.sample_rate == sr and meta.num_channels == 1


def test_wav_roundtrip_8_and_32_bit(tmp_path):
    sr = 8000
    sig = _sine(sr)
    for bits, atol in ((8, 2e-2), (32, 1e-6)):
        path = str(tmp_path / f"t{bits}.wav")
        backends.save(path, pt.to_tensor(sig[None, :]), sr,
                      bits_per_sample=bits)
        meta = backends.info(path)
        assert meta.bits_per_sample == bits
        assert meta.num_frames == len(sig)  # frame count honors sampwidth
        loaded, sr2 = backends.load(path)
        assert sr2 == sr
        np.testing.assert_allclose(loaded.numpy()[0], sig, atol=atol)
