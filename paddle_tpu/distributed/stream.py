"""paddle.distributed.stream analog (reference
distributed/communication/stream/*): the stream-explicit collective
variants.  TPU/XLA has no user-visible communication streams — each
collective is a program op ordered by data dependence — so these
delegate to the synchronous forms (``use_calc_stream`` accepted and
irrelevant)."""
from __future__ import annotations

from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, alltoall_single, broadcast,
    gather, recv, reduce, reduce_scatter, scatter, send,
)

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "gather", "recv", "reduce", "reduce_scatter",
           "scatter", "send"]
