"""ASP — automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/ (prune_model computes n:m masks
with mask_1d/mask_2d algorithms; decorate() wraps the optimizer with
OptimizerWithSparsityGuarantee so masks are re-applied after every step;
supported layers are Linear-like).

TPU-native: masks are plain jnp arrays applied as elementwise multiplies —
under jit.to_static the mask-multiply fuses into the update program.  (The
MXU has no 2:4 sparse path like sparse tensor cores; the value here is
model compression + parity of the pruning/fine-tuning workflow.)
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ...nn.layer import Layer
from ...nn.modules.common import Linear
from ...ops import dispatch
from ...tensor import Tensor

__all__ = ["prune_model", "decorate", "calculate_density", "check_sparsity",
           "reset_excluded_layers", "set_excluded_layers"]

# id(param) -> mask ndarray; the decorated optimizer re-applies these
_masks: Dict[int, jnp.ndarray] = {}
_excluded: set = set()


def set_excluded_layers(layer_names, main_program=None):
    _excluded.update(layer_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x) -> float:
    a = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def _nm_mask_1d(w: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest magnitudes in every group of m along the LAST
    axis (reference mask_1d algorithm)."""
    shape = w.shape
    if shape[-1] % m != 0:
        return np.ones_like(w, dtype=np.float32)
    g = w.reshape(-1, m)
    order = np.argsort(-np.abs(g), axis=1)
    mask = np.zeros_like(g, dtype=np.float32)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(shape)


def check_sparsity(x, n=2, m=4) -> bool:
    a = np.asarray(x._value if isinstance(x, Tensor) else x)
    if a.ndim < 2 or a.shape[-1] % m:
        return False
    g = a.reshape(-1, m)
    return bool((np.count_nonzero(g, axis=1) <= n).all())


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d",
                with_mask=True) -> Dict[int, jnp.ndarray]:
    """Compute n:m masks for every supported (Linear) weight, apply them in
    place, and register them for the decorated optimizer."""
    if mask_algo in ("mask_2d_greedy", "mask_2d_best"):
        raise NotImplementedError(
            f"{mask_algo} (2-D n:m patterns) is not implemented; use "
            "'mask_1d'")
    if mask_algo != "mask_1d":
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    for name, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, Linear) or name in _excluded:
            continue
        w = layer.weight
        mask = _nm_mask_1d(np.asarray(w._value, np.float32), n, m)
        mk = jnp.asarray(mask, w._value.dtype)
        with dispatch.no_grad():
            w._set_value(w._value * mk)
        if with_mask:
            _masks[id(w)] = mk
    return dict(_masks)


def decorate(optimizer):
    """Wrap ``optimizer.step`` so registered masks re-apply after every
    update (reference OptimizerWithSparsityGuarantee) — pruned entries stay
    exactly zero through training."""
    if getattr(optimizer, "_asp_decorated", False):
        return optimizer
    inner_step = optimizer.step

    def step():
        inner_step()
        with dispatch.no_grad():
            for p in optimizer._parameter_list:
                mk = _masks.get(id(p))
                if mk is not None:
                    p._set_value(p._value * mk)

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer
