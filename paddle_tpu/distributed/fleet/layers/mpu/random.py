"""Per-axis RNG state tracking (reference: fleet/layers/mpu/random.py:34
RNGStatesTracker — distinct dropout streams inside vs outside TP regions).
TPU-native: each named state is its own functional Generator."""
from __future__ import annotations

from contextlib import contextmanager

from .....ops.random import Generator, default_generator


class RNGStatesTracker:
    def __init__(self):
        self._states = {}

    def reset(self):
        self._states = {}

    def add(self, name, seed):
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = dict(states)

    @contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self._states:
            self._states[name] = Generator(hash(name) & 0x7FFFFFFF)
        import paddle_tpu.ops.random as R

        prev = R.default_generator
        R.default_generator = self._states[name]
        try:
            yield
        finally:
            R.default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from .....ops.random import seed as set_seed

    base = seed if seed is not None else pyrandom.randint(0, 2**31 - 1)
    _tracker.reset()
    set_seed(base)
    _tracker.add("model_parallel_rng", base + 1)
    _tracker.add("global_seed", base)
