"""ONNX protobuf export (reference python/paddle/onnx/export.py):
the emitted .onnx is decoded with the first-party wire reader and
EXECUTED by a numpy interpreter of the emitted op set — numeric parity
against the eager model is the acceptance bar."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.onnx import export
from paddle_tpu.onnx import proto


def _np_broadcast_reduce(op):
    return {
        "Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
        "Div": np.divide, "Max": np.maximum, "Min": np.minimum,
        "Pow": np.power,
    }[op]


def run_onnx(path, feeds):
    """Minimal numpy interpreter for the exporter's op subset."""
    with open(path, "rb") as f:
        model = proto.parse_model(f.read())
    env = dict(model["initializers"])
    env.update(feeds)
    for node in model["nodes"]:
        op = node["op"]
        x = [env[i] for i in node["inputs"]]
        a = node["attrs"]
        if op in ("Add", "Sub", "Mul", "Div", "Max", "Min", "Pow"):
            out = _np_broadcast_reduce(op)(x[0], x[1])
        elif op == "MatMul":
            out = x[0] @ x[1]
        elif op == "Tanh":
            out = np.tanh(x[0])
        elif op == "Sigmoid":
            out = 1 / (1 + np.exp(-x[0]))
        elif op == "Erf":
            from math import erf
            out = np.vectorize(erf)(x[0]).astype(x[0].dtype)
        elif op == "Exp":
            out = np.exp(x[0])
        elif op == "Log":
            out = np.log(x[0])
        elif op == "Neg":
            out = -x[0]
        elif op == "Sqrt":
            out = np.sqrt(x[0])
        elif op == "Reciprocal":
            out = 1.0 / x[0]
        elif op == "Abs":
            out = np.abs(x[0])
        elif op == "Identity":
            out = x[0]
        elif op == "Transpose":
            out = np.transpose(x[0], a["perm"])
        elif op == "Reshape":
            out = x[0].reshape([int(d) for d in x[1]])
        elif op == "Expand":
            out = np.broadcast_to(x[0], [int(d) for d in x[1]]).copy()
        elif op == "Cast":
            out = x[0].astype(proto.ONNX_TO_NP[a["to"]])
        elif op == "ReduceSum":
            out = np.sum(x[0], axis=tuple(int(d) for d in x[1]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMax":
            out = np.max(x[0], axis=tuple(a["axes"]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "Where":
            out = np.where(x[0], x[1], x[2])
        elif op == "Concat":
            out = np.concatenate(x, axis=a["axis"])
        else:
            raise NotImplementedError(f"interpreter: {op}")
        env[node["outputs"][0]] = out
    return [env[o] for o in model["outputs"]]


def test_export_mlp_numeric_parity(tmp_path):
    pt.seed(0)
    model = pt.nn.Sequential(
        pt.nn.Linear(8, 32), pt.nn.GELU(),
        pt.nn.Linear(32, 16), pt.nn.ReLU(),
        pt.nn.Linear(16, 4))
    model.eval()
    x = pt.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    want = model(x).numpy()

    path = str(tmp_path / "mlp.onnx")
    export(model, path, input_spec=[x])
    got = run_onnx(path, {"x0": x.numpy()})[0]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_export_softmax_layernorm(tmp_path):
    pt.seed(1)

    class Head(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln = pt.nn.LayerNorm(16)
            self.fc = pt.nn.Linear(16, 8)

        def forward(self, x):
            return pt.nn.functional.softmax(self.fc(self.ln(x)), axis=-1)

    model = Head()
    model.eval()
    x = pt.to_tensor(np.random.RandomState(1).randn(2, 16).astype(np.float32))
    want = model(x).numpy()
    path = str(tmp_path / "head.onnx")
    export(model, path, input_spec=[x])
    got = run_onnx(path, {"x0": x.numpy()})[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got.sum(-1), np.ones(2), rtol=1e-5)


def test_export_unsupported_primitive_names_it(tmp_path):
    class Conv(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.c = pt.nn.Conv2D(3, 4, 3)

        def forward(self, x):
            return self.c(x)

    m = Conv()
    m.eval()
    x = pt.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
    with pytest.raises(NotImplementedError, match="primitive"):
        export(m, str(tmp_path / "c.onnx"), input_spec=[x])


def test_non_onnx_path_writes_stablehlo(tmp_path):
    pt.seed(2)
    model = pt.nn.Sequential(pt.nn.Linear(4, 4))
    model.eval()
    x = pt.to_tensor(np.zeros((2, 4), np.float32))
    out = export(model, str(tmp_path / "m"), input_spec=[x])
    import os
    assert any(os.path.exists(str(tmp_path / "m") + ext)
               for ext in (".pdmodel", "", ".json"))
