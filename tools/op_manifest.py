#!/usr/bin/env python
"""Op-coverage manifest (N14 / L2 analog).

The reference generates its op surface from YAML manifests
(paddle/phi/api/yaml/ops.yaml + legacy_ops.yaml); this tool measures the
TPU framework's coverage AGAINST those manifests and writes
OPS_COVERAGE.json — a judgeable, regenerable inventory instead of a
hand-maintained claim.

Usage:  python tools/op_manifest.py [--ref /root/reference] [--out OPS_COVERAGE.json]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# reference op name -> our public name, for renames / fusions that exist
# under a different (jax-idiomatic) spelling
ALIASES = {
    "matmul": "matmul", "elementwise_add": "add", "elementwise_mul": "multiply",
    "elementwise_sub": "subtract", "elementwise_div": "divide",
    "elementwise_pow": "pow",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any", "arg_max": "argmax", "arg_min": "argmin",
    "fill_constant": "full", "top_k": "topk", "one_hot_v2": "one_hot",
    "softmax_with_cross_entropy": "cross_entropy",
    "cross_entropy_with_softmax": "cross_entropy",
    "sigmoid_cross_entropy_with_logits": "binary_cross_entropy_with_logits",
    "flash_attn": "flash_attention",
    "bce_loss": "binary_cross_entropy",
    "kldiv_loss": "kl_div",
    "logsigmoid": "log_sigmoid",
    "frobenius_norm": "norm",
    "fill": "fill_",
    "full_batch_size_like": "full",
    "full_int_array": "full",
    "uniform_inplace": "uniform_",
    "mean_all": "mean",
    "p_norm": "norm",
    "pad3d": "pad",
    "pool2d": "avg_pool2d",
    "pool3d": "avg_pool3d",
    "split_with_num": "split",
    "trans_layout": "transpose",
    "max_pool2d_with_index": "max_pool2d",
    "max_pool3d_with_index": "max_pool3d",
    "assign_out_": "assign",
    "assign_value_": "assign",
    "copy_to": "clone",
    "linear_interp": "interpolate", "bilinear_interp": "interpolate",
    "trilinear_interp": "interpolate", "nearest_interp": "interpolate",
    "bicubic_interp": "interpolate",
}

# reference ops whose surface in this framework is a CLASS or module
# attribute rather than a flat function; each value is verified by
# attribute lookup at generation time
CLASS_COVERAGE = {
    "adam_": "optimizer.Adam", "adamw_": "optimizer.AdamW",
    "adamax_": "optimizer.Adamax", "adagrad_": "optimizer.Adagrad",
    "sgd_": "optimizer.SGD", "momentum_": "optimizer.Momentum",
    "rmsprop_": "optimizer.RMSProp", "lamb_": "optimizer.Lamb",
    "lars_momentum_": "distributed.fleet.meta_optimizers.LarsMomentum",
    "dgc_momentum": "distributed.fleet.meta_optimizers.DGCMomentum",
    "accuracy": "metric.Accuracy", "auc": "metric.Auc",
    "clip_by_norm": "nn.ClipGradByNorm",
    "check_finite_and_unscale_": "amp.GradScaler",
    "update_loss_scaling_": "amp.GradScaler",
    "check_numerics": "amp.debugging.check_numerics",
    "fft_c2c": "fft.fft", "fft_r2c": "fft.rfft", "fft_c2r": "fft.irfft",
    "depthwise_conv2d": "nn.functional.conv2d",
    "send_u_recv": "geometric.send_u_recv",
    "send_ue_recv": "geometric.send_ue_recv",
    "send_uv": "geometric.send_uv",
    "graph_send_recv": "geometric.send_u_recv",
    "segment_pool": "geometric.segment_sum",
    "dirichlet": "distribution.Dirichlet",
    "nms": "vision.ops.nms",
    "box_coder": "vision.ops.box_coder",
    "roi_align": "vision.ops.roi_align",
    "prior_box": "vision.ops.prior_box",
    "edit_distance": "vision.ops.edit_distance",
    "spectral_norm": "nn.SpectralNorm",
    "margin_cross_entropy": "nn.functional.margin_cross_entropy",
    "lookahead": "incubate.optimizer.LookAhead",
    "decode_jpeg": "vision.ops.decode_jpeg",
    "roi_pool": "vision.ops.roi_pool",
    "fill_diagonal": "fill_diagonal_",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "repeat_interleave_with_tensor_index": "ops.repeat_interleave",
    "npu_identity": "ops.clone",
    "rnn": "nn.RNN",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "viterbi_decode": "text.viterbi_decode",
    "temporal_shift": "nn.functional.temporal_shift",
    "unpool": "nn.functional.max_unpool2d",
    "matrix_rank_tol": "ops.linalg.matrix_rank",
    "warpctc": "nn.functional.ctc_loss",
    "memory_efficient_attention": "nn.functional.scaled_dot_product_attention",
    "merged_adam_": "optimizer.Adam",
    "merged_momentum_": "optimizer.Momentum",
    "adadelta_": "optimizer.Adadelta",
    "tanh_shrink": "nn.functional.tanhshrink",
    "grid_sample": "nn.functional.grid_sample",
    "affine_grid": "nn.functional.affine_grid",
    "channel_shuffle": "nn.functional.channel_shuffle",
    "huber_loss": "nn.functional.huber_loss",
    "log_loss": "nn.functional.log_loss",
    "fused_adam_": "ops.pallas_kernels.fused_adamw.fused_adamw_update",
    "yolo_box": "vision.ops.yolo_box",
    "yolo_loss": "vision.ops.yolo_loss",
    "generate_proposals": "vision.ops.generate_proposals",
    "distribute_fpn_proposals": "vision.ops.distribute_fpn_proposals",
    "matrix_nms": "vision.ops.matrix_nms",
    "multiclass_nms3": "vision.ops.multiclass_nms",
    "psroi_pool": "vision.ops.psroi_pool",
    "deformable_conv": "vision.ops.deform_conv2d",
    "warprnnt": "nn.functional.rnnt_loss",
    "unpool3d": "nn.functional.max_unpool3d",
    "average_accumulates_": "incubate.optimizer.ModelAverage",
    "merge_selected_rows": "incubate.merge_selected_rows",
}

# reference ops deliberately NOT implemented, with the architectural
# reason — reported separately so `missing` stays an honest work list
DESCOPED = {
    "coalesce_tensor": "grad-buffer fusion feeding fused allreduce; XLA "
                       "buffer assignment + SPMD collectives make the "
                       "user-facing op surface meaningless on TPU",
}


def reference_ops(ref_root: str):
    ops = set()
    for name in ("ops.yaml", "legacy_ops.yaml"):
        path = os.path.join(ref_root, "paddle/phi/api/yaml", name)
        if not os.path.exists(path):
            continue
        for line in open(path, encoding="utf-8"):
            m = re.match(r"^- op\s*:\s*([a-zA-Z0-9_]+)", line)
            if m:
                ops.add(m.group(1))
    return ops


def our_surface():
    """Public callables on the op-bearing namespaces."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as pt

    names = set()
    spaces = [pt, pt.ops, pt.nn.functional, pt.linalg if hasattr(pt, "linalg")
              else pt.ops, pt.fft, pt.signal, pt.sparse, pt.geometric]
    for sp in spaces:
        for n in dir(sp):
            if n.startswith("_"):
                continue
            if callable(getattr(sp, n, None)):
                names.add(n)
    # pallas / fusion kernels
    from paddle_tpu.ops import pallas_kernels as pk

    for n in dir(pk):
        if not n.startswith("_"):
            names.add(n)
    try:
        from paddle_tpu.ops.pallas_kernels import flash_attention as fa  # noqa
        names.add("flash_attention")
    except Exception:
        pass
    from paddle_tpu.ops.pallas_kernels import fused_adamw  # noqa

    names.add("fused_adamw")
    return names


def _resolve_dotted(path):
    import importlib

    import paddle_tpu as pt

    obj = pt
    parts = path.split(".")
    for i, part in enumerate(parts):
        nxt = getattr(obj, part, None)
        if nxt is None:
            # attribute chains can cross not-yet-imported submodules;
            # resolution must not depend on import side effects elsewhere
            try:
                nxt = importlib.import_module(
                    "paddle_tpu." + ".".join(parts[:i + 1]))
            except ImportError:
                return None
        obj = nxt
    return obj


def _resolve_flat(name):
    """Find the callable behind a flat surface name on the op namespaces."""
    import paddle_tpu as pt

    spaces = [pt, pt.ops, pt.nn.functional,
              getattr(pt, "linalg", pt.ops), pt.fft, pt.signal, pt.sparse,
              pt.geometric]
    for sp in spaces:
        obj = getattr(sp, name, None)
        if callable(obj):
            return obj
    try:
        from paddle_tpu.ops import pallas_kernels as pk
        obj = getattr(pk, name, None)
        if callable(obj):
            return obj
    except Exception:
        pass
    return None


def _is_source_stub(fn):
    """True when the implementation is an unconditional NotImplementedError
    raise (a coverage-gaming stub), judged from the AST — catches stubs that
    hide behind signature TypeErrors during the smoke call, regardless of
    docstring shape."""
    import ast
    import inspect
    import textwrap

    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return False
    defs = [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    if not defs:
        return False
    body = defs[0].body
    # drop a leading docstring expression
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant):
        body = body[1:]
    if len(body) != 1 or not isinstance(body[0], ast.Raise):
        return False
    exc = body[0].exc
    name = getattr(exc, "id", None) or getattr(
        getattr(exc, "func", None), "id", None)
    return name == "NotImplementedError"


def _smoke_fixtures():
    import numpy as np
    import paddle_tpu as pt

    rng = np.random.RandomState(0)
    f = pt.to_tensor(rng.rand(2, 3).astype(np.float32) + 0.1)   # positive
    fs = pt.to_tensor(rng.randn(2, 3).astype(np.float32))       # signed
    sq = pt.to_tensor(rng.randn(3, 3).astype(np.float32))       # square
    spd = pt.to_tensor((np.eye(3) * 3 + rng.rand(3, 3) * 0.1
                        + (rng.rand(3, 3) * 0.1).T).astype(np.float32))
    i = pt.to_tensor(np.array([[1, 0, 2], [2, 1, 0]], np.int64))
    b = pt.to_tensor(np.array([[True, False, True],
                               [False, True, True]]))
    frac = pt.to_tensor(rng.rand(2, 3).astype(np.float32) * 0.8 + 0.1)
    vec = pt.to_tensor(rng.randn(6).astype(np.float32))
    return {"f": f, "fs": fs, "sq": sq, "spd": spd, "i": i, "b": b,
            "frac": frac, "vec": vec}


def _generic_attempts(fx):
    """Argument tuples tried in order for ops without an explicit smoke."""
    f, fs, sq, i, b = fx["f"], fx["fs"], fx["sq"], fx["i"], fx["b"]
    return [
        (fx["frac"],), (f,), (fs,), (sq,), (fx["vec"],), (i,), (b,),
        (f, f), (fs, fs), (sq, sq), (i, i), (b, b),
        (f, 1.0), (f, 2), (fs, 0), (f, [2, 3]), (i, 3),
        (f, f, f), (b, f, f),
    ]


def _explicit_smokes():
    """Per-op invocations for surfaces whose signatures the generic
    attempts can't satisfy.  Keyed by the COVERED TARGET name."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    t = lambda a, **k: pt.to_tensor(np.asarray(a), **k)
    img = t(rng.randn(1, 3, 8, 8).astype(np.float32))
    img1 = t(rng.randn(1, 3, 8).astype(np.float32))
    img3 = t(rng.randn(1, 3, 4, 8, 8).astype(np.float32))
    w2 = t(rng.randn(4, 3, 3, 3).astype(np.float32))
    lab = t(np.array([1, 0], np.int64))
    logits = t(rng.randn(2, 4).astype(np.float32))
    probs = t(np.abs(rng.rand(2, 4).astype(np.float32)) + 0.1)
    bsh = t(rng.randn(2, 8, 2, 4).astype(np.float32))  # [b,s,h,d]
    seq = t(rng.randn(4, 2, 6).astype(np.float32))     # [T,B,C]
    emb_w = t(rng.randn(10, 4).astype(np.float32))

    return {
        "conv2d": lambda: F.conv2d(img, w2, padding=1),
        "conv1d": lambda: F.conv1d(img1, t(rng.randn(4, 3, 3).astype(np.float32)), padding=1),
        "conv3d": lambda: F.conv3d(img3, t(rng.randn(4, 3, 2, 3, 3).astype(np.float32))),
        "conv2d_transpose": lambda: F.conv2d_transpose(img, t(rng.randn(4, 3, 3, 3).astype(np.float32))),
        "conv1d_transpose": lambda: F.conv1d_transpose(img1, t(rng.randn(4, 3, 3).astype(np.float32))),
        "conv3d_transpose": lambda: F.conv3d_transpose(img3, t(rng.randn(4, 3, 2, 3, 3).astype(np.float32))),
        "avg_pool2d": lambda: F.avg_pool2d(img, 2),
        "avg_pool3d": lambda: F.avg_pool3d(img3, 2),
        "max_pool2d": lambda: F.max_pool2d(img, 2, return_mask=True),
        "max_pool3d": lambda: F.max_pool3d(img3, 2, return_mask=True),
        "max_unpool2d": lambda: F.max_unpool2d(
            *F.max_pool2d(img, 2, return_mask=True), kernel_size=2),
        "interpolate": lambda: F.interpolate(img, scale_factor=2, mode="nearest"),
        "cross_entropy": lambda: F.cross_entropy(logits, lab),
        "binary_cross_entropy": lambda: F.binary_cross_entropy(
            t(rng.rand(2, 4).astype(np.float32)), t(rng.rand(2, 4).astype(np.float32))),
        "binary_cross_entropy_with_logits": lambda: F.binary_cross_entropy_with_logits(
            logits, t(rng.rand(2, 4).astype(np.float32))),
        "ctc_loss": lambda: F.ctc_loss(seq, t(np.array([[1, 2], [2, 1]], np.int64)),
                                       t(np.array([4, 4], np.int64)),
                                       t(np.array([2, 2], np.int64))),
        "flash_attention": lambda: F.flash_attention(bsh, bsh, bsh),
        "scaled_dot_product_attention": lambda: F.scaled_dot_product_attention(bsh, bsh, bsh),
        "embedding": lambda: F.embedding(t(np.array([[1, 2]], np.int64)), emb_w),
        "one_hot": lambda: F.one_hot(lab, 4),
        "kl_div": lambda: F.kl_div(F.log_softmax(logits), F.softmax(logits)),
        "nll_loss": lambda: F.nll_loss(F.log_softmax(logits), lab),
        "margin_cross_entropy": lambda: F.margin_cross_entropy(
            F.normalize(logits), lab),
        "softmax_with_cross_entropy": lambda: F.cross_entropy(logits, lab),
        "gather": lambda: pt.ops.gather(logits, t(np.array([0, 1], np.int64))),
        "gather_nd": lambda: pt.ops.gather_nd(logits, t(np.array([[0, 1]], np.int64))),
        "scatter": lambda: pt.ops.scatter(logits, t(np.array([0, 1], np.int64)), logits),
        "scatter_nd": lambda: pt.ops.scatter_nd(
            t(np.array([[1], [2]], np.int64)), t(rng.randn(2, 4).astype(np.float32)), [4, 4]),
        "scatter_nd_add": lambda: pt.ops.scatter_nd_add(
            logits, t(np.array([[0], [1]], np.int64)), logits),
        "index_select": lambda: pt.ops.index_select(logits, t(np.array([0, 1], np.int64))),
        "index_add": lambda: pt.ops.index_add(
            logits, t(np.array([0, 1], np.int64)), 0, logits),
        "index_put": lambda: pt.ops.index_put(
            logits, (t(np.array([0], np.int64)),), t(rng.randn(1, 4).astype(np.float32))),
        "put_along_axis": lambda: pt.ops.put_along_axis(
            logits, t(np.array([[0], [1]], np.int64)), 1.0, 1),
        "take_along_axis": lambda: pt.ops.take_along_axis(
            logits, t(np.array([[0], [1]], np.int64)), 1),
        "topk": lambda: pt.ops.topk(logits, 2),
        "pad": lambda: F.pad(img, [1, 1, 1, 1]),
        "dropout": lambda: F.dropout(logits, 0.5),
        "batch_norm": lambda: pt.nn.BatchNorm2D(3)(img),
        "layer_norm": lambda: F.layer_norm(logits, 4,
                                           t(np.ones(4, np.float32)), t(np.zeros(4, np.float32))),
        "instance_norm": lambda: pt.nn.InstanceNorm2D(3)(img),
        "group_norm": lambda: pt.nn.GroupNorm(1, 3)(img),
        "local_response_norm": lambda: F.local_response_norm(img, 3),
        "prelu": lambda: F.prelu(logits, t(np.array([0.2], np.float32))),
        "pixel_shuffle": lambda: F.pixel_shuffle(t(rng.randn(1, 4, 3, 3).astype(np.float32)), 2),
        "pixel_unshuffle": lambda: F.pixel_unshuffle(img, 2),
        "linear": lambda: F.linear(logits, t(rng.randn(4, 5).astype(np.float32))),
        "bilinear": lambda: F.bilinear(logits, logits,
                                       t(rng.randn(3, 4, 4).astype(np.float32))),
        "bincount": lambda: pt.ops.bincount(t(np.array([0, 1, 1], np.int64))),
        "multinomial": lambda: pt.ops.multinomial(probs, 1),
        "bernoulli": lambda: pt.ops.bernoulli(t(np.full((2, 2), 0.5, np.float32))),
        "full": lambda: pt.ops.full([2, 2], 1.0),
        "arange": lambda: pt.ops.arange(0, 5),
        "linspace": lambda: pt.ops.linspace(0, 1, 5),
        "logspace": lambda: pt.ops.logspace(0, 1, 5),
        "eye": lambda: pt.ops.eye(3),
        "tril_indices": lambda: pt.ops.tril_indices(3, 3, 0),
        "triu_indices": lambda: pt.ops.triu_indices(3, 3, 0),
        "randint": lambda: pt.ops.randint(0, 5, [2, 2]),
        "randperm": lambda: pt.ops.randperm(5),
        "rand": lambda: pt.ops.rand([2, 2]),
        "randn": lambda: pt.ops.randn([2, 2]),
        "normal": lambda: pt.ops.normal(0.0, 1.0, [2, 2]),
        "uniform": lambda: pt.ops.uniform([2, 2]),
        "uniform_": lambda: pt.ops.uniform_(t(rng.randn(2, 2).astype(np.float32)), 0, 1),
        "exponential_": lambda: pt.ops.exponential_(t(np.ones((2, 2), np.float32))),
        "poisson": lambda: pt.ops.poisson(t(np.ones((2, 2), np.float32))),
        "standard_gamma": lambda: pt.ops.standard_gamma(t(np.ones((2, 2), np.float32))),
        "reshape": lambda: pt.ops.reshape(logits, [4, 2]),
        "transpose": lambda: pt.ops.transpose(logits, [1, 0]),
        "squeeze": lambda: pt.ops.squeeze(t(rng.randn(1, 2).astype(np.float32))),
        "unsqueeze": lambda: pt.ops.unsqueeze(logits, 0),
        "concat": lambda: pt.ops.concat([logits, logits]),
        "stack": lambda: pt.ops.stack([logits, logits]),
        "split": lambda: pt.ops.split(logits, 2),
        "chunk": lambda: pt.ops.chunk(logits, 2),
        "tile": lambda: pt.ops.tile(logits, [2, 1]),
        "expand": lambda: pt.ops.expand(t(rng.randn(1, 4).astype(np.float32)), [3, 4]),
        "expand_as": lambda: pt.ops.expand_as(
            t(rng.randn(1, 4).astype(np.float32)), logits),
        "broadcast_to": lambda: pt.ops.broadcast_to(
            t(rng.randn(1, 4).astype(np.float32)), [3, 4]),
        "flip": lambda: pt.ops.flip(logits, [0]),
        "roll": lambda: pt.ops.roll(logits, 1),
        "cumsum": lambda: pt.ops.cumsum(logits, 0),
        "cumprod": lambda: pt.ops.cumprod(logits, 0),
        "cummax": lambda: pt.ops.cummax(logits, 0),
        "cummin": lambda: pt.ops.cummin(logits, 0),
        "logcumsumexp": lambda: pt.ops.logcumsumexp(logits, 0),
        "unbind": lambda: pt.ops.unbind(logits),
        "unstack": lambda: pt.ops.unstack(logits),
        "strided_slice": lambda: pt.ops.strided_slice(logits, [0], [0], [2], [1]),
        "slice": lambda: pt.ops.slice(logits, [0], [0], [1]),
        "crop": lambda: pt.ops.crop(logits, [1, 2]),
        "argsort": lambda: pt.ops.argsort(logits),
        "sort": lambda: pt.ops.sort(logits),
        "searchsorted": lambda: pt.ops.searchsorted(
            t(np.array([1.0, 2.0, 3.0], np.float32)), t(np.array([1.5], np.float32))),
        "unique": lambda: pt.ops.unique(t(np.array([1, 1, 2], np.int64))),
        "unique_consecutive": lambda: pt.ops.unique_consecutive(
            t(np.array([1, 1, 2], np.int64))),
        "masked_select": lambda: pt.ops.masked_select(
            logits, t(np.ones((2, 4), bool))),
        "masked_fill": lambda: pt.ops.masked_fill(
            logits, t(np.zeros((2, 4), bool)), 0.0),
        "where": lambda: pt.ops.where(t(np.ones((2, 4), bool)), logits, logits),
        "clip": lambda: pt.ops.clip(logits, -1.0, 1.0),
        "matmul": lambda: pt.ops.matmul(logits, t(rng.randn(4, 2).astype(np.float32))),
        "mm": lambda: pt.ops.mm(logits, t(rng.randn(4, 2).astype(np.float32))),
        "bmm": lambda: pt.ops.bmm(t(rng.randn(2, 3, 4).astype(np.float32)),
                                  t(rng.randn(2, 4, 3).astype(np.float32))),
        "addmm": lambda: pt.ops.addmm(
            t(rng.randn(2, 2).astype(np.float32)), logits,
            t(rng.randn(4, 2).astype(np.float32))),
        "einsum": lambda: pt.ops.einsum("ij,jk->ik", logits,
                                        t(rng.randn(4, 2).astype(np.float32))),
        "norm": lambda: pt.ops.norm(logits),
        "dist": lambda: pt.ops.dist(logits, logits),
        "cdist": lambda: pt.ops.cdist(logits, logits),
        "cross": lambda: pt.ops.cross(t(rng.randn(2, 3).astype(np.float32)),
                                      t(rng.randn(2, 3).astype(np.float32))),
        "dot": lambda: pt.ops.dot(t(rng.randn(4).astype(np.float32)),
                                  t(rng.randn(4).astype(np.float32))),
        "tensordot": lambda: pt.ops.tensordot(logits, logits, axes=2),
        "kron": lambda: pt.ops.kron(logits, logits),
        "outer": lambda: pt.ops.outer(t(rng.randn(3).astype(np.float32)),
                                      t(rng.randn(3).astype(np.float32))),
        "inner": lambda: pt.ops.inner(t(rng.randn(3).astype(np.float32)),
                                      t(rng.randn(3).astype(np.float32))),
        "mv": lambda: pt.ops.mv(logits, t(rng.randn(4).astype(np.float32))),
        "histogram": lambda: pt.ops.histogram(logits, 4),
        "histogramdd": lambda: pt.ops.histogramdd(
            t(rng.randn(5, 2).astype(np.float32)), 3),
        "quantile": lambda: pt.ops.quantile(logits, 0.5),
        "nanquantile": lambda: pt.ops.nanquantile(logits, 0.5),
        "kthvalue": lambda: pt.ops.kthvalue(logits, 2),
        "mode": lambda: pt.ops.mode(logits),
        "median": lambda: pt.ops.median(logits),
        "nanmedian": lambda: pt.ops.nanmedian(logits),
        "diff": lambda: pt.ops.diff(logits),
        "trapezoid": lambda: pt.ops.trapezoid(logits),
        "cumulative_trapezoid": lambda: pt.ops.cumulative_trapezoid(logits),
        "diag": lambda: pt.ops.diag(t(rng.randn(3).astype(np.float32))),
        "diagflat": lambda: pt.ops.diagflat(t(rng.randn(3).astype(np.float32))),
        "diagonal": lambda: pt.ops.diagonal(t(rng.randn(3, 3).astype(np.float32))),
        "diag_embed": lambda: pt.ops.diag_embed(logits),
        "fill_diagonal_": lambda: pt.ops.fill_diagonal_(
            t(rng.randn(3, 3).astype(np.float32)), 0.0),
        "fill_diagonal_tensor": lambda: pt.ops.fill_diagonal_tensor(
            t(rng.randn(3, 3).astype(np.float32)), t(np.zeros(3, np.float32))),
        "trace": lambda: pt.ops.trace(t(rng.randn(3, 3).astype(np.float32))),
        "rot90": lambda: pt.ops.rot90(logits),
        "meshgrid": lambda: pt.ops.meshgrid(t(rng.randn(2).astype(np.float32)),
                                            t(rng.randn(3).astype(np.float32))),
        "repeat_interleave": lambda: pt.ops.repeat_interleave(logits, 2),
        "renorm": lambda: pt.ops.renorm(logits, 2.0, 0, 1.0),
        "multi_dot": lambda: pt.ops.linalg.multi_dot(
            [logits, t(rng.randn(4, 2).astype(np.float32))]),
        "as_complex": lambda: pt.ops.as_complex(
            t(rng.randn(3, 2).astype(np.float32))),
        "as_real": lambda: pt.ops.as_real(pt.ops.as_complex(
            t(rng.randn(3, 2).astype(np.float32)))),
        "complex": lambda: pt.ops.complex(logits, logits),
        "polar": lambda: pt.ops.polar(probs, logits),
        "pad3d": lambda: F.pad(img3, [1, 1, 1, 1, 1, 1]),
        "temporal_shift": lambda: F.temporal_shift(
            t(rng.randn(4, 4, 2, 2).astype(np.float32)), 2, 0.25),
        "affine_grid": lambda: F.affine_grid(
            t(rng.randn(1, 2, 3).astype(np.float32)), [1, 3, 4, 4]),
        "grid_sample": lambda: F.grid_sample(
            img, t(rng.rand(1, 8, 8, 2).astype(np.float32) * 2 - 1)),
        "channel_shuffle": lambda: F.channel_shuffle(
            t(rng.randn(1, 4, 3, 3).astype(np.float32)), 2),
        "gumbel_softmax": lambda: F.gumbel_softmax(logits),
        "log_softmax": lambda: F.log_softmax(logits),
        "softmax": lambda: F.softmax(logits),
        "unfold": lambda: F.unfold(img, 3),
        "fold": lambda: F.fold(F.unfold(img, 3), [8, 8], 3),
        "gaussian": lambda: pt.ops.gaussian([2, 2]),
        "gather_tree": lambda: pt.ops.gather_tree(
            t(np.zeros((2, 1, 2), np.int64)),
            t(np.zeros((2, 1, 2), np.int64))),
        "flash_attn_unpadded": lambda: F.flash_attn_unpadded(
            t(rng.randn(8, 2, 4).astype(np.float32)),
            t(rng.randn(8, 2, 4).astype(np.float32)),
            t(rng.randn(8, 2, 4).astype(np.float32)),
            t(np.array([0, 5, 8], np.int32)), t(np.array([0, 5, 8], np.int32))),
        "fused_adamw_update": lambda: __import__(
            "paddle_tpu.ops.pallas_kernels.fused_adamw",
            fromlist=["fused_adamw_update"]).fused_adamw_update(
                *(np.zeros((2, 130), np.float32),) * 4, 1e-3, 0.9, 0.999,
                interpret=True),
        "cast": lambda: pt.ops.cast(logits, "int32"),
        "zeros": lambda: pt.ops.zeros([2, 2]),
        "ones": lambda: pt.ops.ones([2, 2]),
        "empty": lambda: pt.ops.empty([2, 2]),
        "frame": lambda: pt.signal.frame(
            t(np.arange(8, dtype=np.float32)), 4, 2),
        "matrix_power": lambda: pt.ops.matrix_power(
            t(np.eye(3, dtype=np.float32)), 2),
        "shard_index": lambda: pt.ops.shard_index(
            t(np.array([[1], [5]], np.int64)), 8, 2, 0),
        "nms": lambda: pt.vision.ops.nms(
            t(np.array([[0, 0, 1, 1], [0, 0, 1.1, 1.1]], np.float32)), 0.5),
        "roi_align": lambda: pt.vision.ops.roi_align(
            img, t(np.array([[0, 0, 4, 4]], np.float32)),
            t(np.array([1], np.int32)), 2),
        "roi_pool": lambda: pt.vision.ops.roi_pool(
            img, t(np.array([[0, 0, 4, 4]], np.float32)),
            t(np.array([1], np.int32)), 2),
        "prior_box": lambda: pt.vision.ops.prior_box(
            img, img, min_sizes=[2.0]),
        "box_coder": lambda: pt.vision.ops.box_coder(
            t(np.array([[0, 0, 1, 1]], np.float32)),
            t(np.array([0.1, 0.1, 0.2, 0.2], np.float32)),
            t(np.array([[[0, 0, 1, 1]]], np.float32))),
        "viterbi_decode": lambda: pt.text.viterbi_decode(
            t(rng.randn(1, 3, 4).astype(np.float32)),
            t(rng.randn(4, 4).astype(np.float32)),
            t(np.array([3], np.int64))),
        "send_u_recv": lambda: pt.geometric.send_u_recv(
            t(rng.randn(4, 2).astype(np.float32)),
            t(np.array([0, 1], np.int64)), t(np.array([1, 2], np.int64))),
        "send_ue_recv": lambda: pt.geometric.send_ue_recv(
            t(rng.randn(4, 2).astype(np.float32)),
            t(rng.randn(2, 2).astype(np.float32)),
            t(np.array([0, 1], np.int64)), t(np.array([1, 2], np.int64))),
        "send_uv": lambda: pt.geometric.send_uv(
            t(rng.randn(4, 2).astype(np.float32)),
            t(rng.randn(4, 2).astype(np.float32)),
            t(np.array([0, 1], np.int64)), t(np.array([1, 2], np.int64))),
        "yolo_box": lambda: pt.vision.ops.yolo_box(
            t(rng.randn(1, 14, 4, 4).astype(np.float32)),
            t(np.array([[128, 128]], np.int32)),
            anchors=[10, 13, 16, 30], class_num=2, conf_thresh=0.01,
            downsample_ratio=32),
        "yolo_loss": lambda: pt.vision.ops.yolo_loss(
            t(rng.randn(1, 14, 4, 4).astype(np.float32)),
            t(rng.rand(1, 3, 4).astype(np.float32) * 0.5 + 0.2),
            t(rng.randint(0, 2, (1, 3)).astype(np.int32)),
            anchors=[10, 13, 16, 30], anchor_mask=[0, 1], class_num=2,
            ignore_thresh=0.7, downsample_ratio=32),
        "generate_proposals": lambda: pt.vision.ops.generate_proposals(
            t(rng.rand(1, 3, 4, 4).astype(np.float32)),
            t(rng.randn(1, 12, 4, 4).astype(np.float32) * 0.1),
            t(np.array([[64, 64]], np.float32)),
            t((rng.rand(4, 4, 3, 4) * 64).astype(np.float32)),
            t(np.ones((4, 4, 3, 4), np.float32) * 0.1),
            pre_nms_top_n=10, post_nms_top_n=5),
        "distribute_fpn_proposals":
            lambda: pt.vision.ops.distribute_fpn_proposals(
                t((rng.rand(6, 4) * np.array([10, 10, 200, 200]))
                  .astype(np.float32)), 2, 5, 4, 224),
        "matrix_nms": lambda: pt.vision.ops.matrix_nms(
            t(rng.rand(1, 6, 4).astype(np.float32)),
            t(rng.rand(1, 2, 6).astype(np.float32)),
            score_threshold=0.1, post_threshold=0.1, nms_top_k=4,
            keep_top_k=4),
        "multiclass_nms": lambda: pt.vision.ops.multiclass_nms(
            t(rng.rand(1, 6, 4).astype(np.float32)),
            t(rng.rand(1, 2, 6).astype(np.float32)),
            score_threshold=0.1, nms_top_k=4, keep_top_k=4),
        "psroi_pool": lambda: pt.vision.ops.psroi_pool(
            t(rng.randn(1, 8, 8, 8).astype(np.float32)),
            t(np.array([[0, 0, 4, 4]], np.float32)),
            t(np.array([1], np.int32)), 2),
        "deform_conv2d": lambda: pt.vision.ops.deform_conv2d(
            t(rng.randn(1, 3, 6, 6).astype(np.float32)),
            t(np.zeros((1, 18, 4, 4), np.float32)),
            t(rng.randn(4, 3, 3, 3).astype(np.float32))),
        "rnnt_loss": lambda: F.rnnt_loss(
            t(rng.randn(1, 4, 3, 4).astype(np.float32)),
            t(rng.randint(1, 4, (1, 2)).astype(np.int32)),
            t(np.array([4], np.int64)), t(np.array([2], np.int64))),
        "hsigmoid_loss": lambda: F.hsigmoid_loss(
            t(rng.randn(3, 4).astype(np.float32)),
            t(rng.randint(0, 6, (3,)).astype(np.int64)), 6,
            t(rng.randn(5, 4).astype(np.float32))),
        "class_center_sample": lambda: F.class_center_sample(
            t(np.array([1, 3], np.int64)), 10, 4),
        "max_unpool3d": lambda: F.max_unpool3d(
            *F.max_pool3d(t(rng.randn(1, 2, 4, 4, 4).astype(np.float32)),
                          2, return_mask=True), kernel_size=2),
        "reindex_graph": lambda: pt.geometric.reindex_graph(
            t(np.array([0, 1], np.int64)),
            t(np.array([3, 0, 2], np.int64)),
            t(np.array([2, 1], np.int32))),
        "weighted_sample_neighbors":
            lambda: pt.geometric.weighted_sample_neighbors(
                t(np.array([1, 2, 0], np.int64)),
                t(np.array([0, 2, 3, 3], np.int64)),
                t(np.array([0.5, 0.2, 0.9], np.float32)),
                t(np.array([0, 1], np.int64)), sample_size=1),
        "merge_selected_rows": lambda: pt.incubate.merge_selected_rows(
            pt.incubate.SelectedRows(
                [1, 0, 1], np.ones((3, 2), np.float32), height=4)),
    }


def smoke_covered(covered):
    """Execute every covered mapping; return (executed, static_ok, stubs).

    - ``executed``: the mapping's callable ran on tiny CPU inputs
    - ``static_ok``: not executed (signature not synthesized / class
      target) but source-verified as a real body
    - ``stubs``: raised NotImplementedError when called, or the body IS an
      unconditional raise — these FAIL coverage
    """
    explicit = _explicit_smokes()
    executed, static_ok, stubs, unresolved = [], [], [], []
    broken = {}
    for op, target in sorted(covered.items()):
        # fresh fixtures per op: in-place ops (fill_, increment, ...)
        # mutate their inputs, and a shared fixture would leak that
        # mutation into every later probe
        attempts = _generic_attempts(_smoke_fixtures())
        fn = (_resolve_dotted(target) if "." in target
              else _resolve_flat(target))
        if fn is None:
            unresolved.append(op)
            continue
        probe = fn
        if isinstance(fn, type):        # class target: constructor probe
            if _is_source_stub(getattr(fn, "__init__", fn)):
                stubs.append(op)
            else:
                static_ok.append(op)
            continue
        if _is_source_stub(probe):
            stubs.append(op)
            continue
        key = target.split(".")[-1]
        ran = False
        if key in explicit or target in explicit:
            try:
                (explicit.get(target) or explicit[key])()
                ran = True
            except NotImplementedError:
                stubs.append(op)
                continue
            except Exception as exc:
                # the dedicated fixture is the contract for this op: a
                # crash means either the op or its smoke regressed, and
                # silently falling back to generic attempts would let a
                # broken op keep counting as covered
                broken[op] = (f"{type(exc).__name__}: "
                              f"{str(exc)[:100]}")
                continue
        if not ran:
            for args in attempts:
                try:
                    probe(*args)
                    ran = True
                    break
                except NotImplementedError:
                    stubs.append(op)
                    ran = None
                    break
                except Exception:
                    continue
        if ran is None:
            continue
        (executed if ran else static_ok).append(op)
    return executed, static_ok, stubs, unresolved, broken


def classify(ref_ops, ours):
    covered, missing = {}, []
    for op in sorted(ref_ops):
        base = op[:-1] if op.endswith("_") else op  # inplace variants
        target = None
        for cand in (op, base, ALIASES.get(op), ALIASES.get(base)):
            if cand and cand in ours:
                target = cand
                break
        if target is None:
            dotted = CLASS_COVERAGE.get(op) or CLASS_COVERAGE.get(base)
            if dotted and _resolve_dotted(dotted) is not None:
                target = dotted
        if target:
            covered[op] = target
        else:
            missing.append(op)
    return covered, missing


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--out", default=os.path.join(REPO, "OPS_COVERAGE.json"))
    args = ap.parse_args()
    ref_ops = reference_ops(args.ref)
    ours = our_surface()
    covered, missing = classify(ref_ops, ours)
    # integrity pass (round-4 verdict weak #2): a mapping only counts as
    # covered if it EXECUTES on tiny CPU inputs (or is a source-verified
    # real body when no generic signature fits); NotImplementedError
    # stubs are failed into the missing list
    executed, static_ok, stubs, unresolved, broken = smoke_covered(covered)
    for op in stubs:
        covered.pop(op, None)
        missing.append(op + " (stub: raises NotImplementedError)")
    for op in unresolved:
        covered.pop(op, None)
        missing.append(op + " (unresolvable covered_map target)")
    for op, why in broken.items():
        covered.pop(op, None)
        missing.append(f"{op} (smoke failed: {why})")
    descoped = {op: why for op, why in DESCOPED.items() if op in missing}
    missing = sorted(m for m in missing if m not in descoped)
    doc = {
        "reference_manifest_ops": len(ref_ops),
        "covered": len(covered),
        "coverage_pct": round(100.0 * len(covered) / max(len(ref_ops), 1), 1),
        "descoped": descoped,
        "covered_executed": len(executed),
        "covered_static_only": len(static_ok),
        "static_only_ops": static_ok,
        "our_public_callables": len(ours),
        "missing": missing,
        "covered_map": covered,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
    print(f"{doc['covered']}/{doc['reference_manifest_ops']} reference "
          f"manifest ops covered ({doc['coverage_pct']}%); "
          f"wrote {args.out}")


if __name__ == "__main__":
    main()
