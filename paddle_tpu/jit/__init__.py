"""jit: trace-and-compile (dy2static analog) + program save/load."""
from .api import StaticFunction, in_tracing, not_to_static, to_static  # noqa: F401
from .save_load import load, save  # noqa: F401
