"""vision.transforms (reference: python/paddle/vision/transforms/) —
numpy-based host preprocessing."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8 → CHW float32 in [0,1] (numpy; Tensor conversion happens at
    collate)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img)
        if a.dtype == np.uint8:
            a = a.astype(np.float32) / 255.0
        if a.ndim == 2:
            a = a[..., None]
        if self.data_format == "CHW":
            a = np.transpose(a, (2, 0, 1))
        return a.astype(np.float32)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (a - m) / s


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        a = np.asarray(img)
        try:
            from PIL import Image

            mode_in = Image.fromarray(a if a.dtype == np.uint8 else a.astype(np.uint8))
            return np.asarray(mode_in.resize((self.size[1], self.size[0])))
        except ImportError:
            # nearest-neighbor fallback
            h, w = a.shape[:2]
            ys = (np.arange(self.size[0]) * h // self.size[0]).clip(0, h - 1)
            xs = (np.arange(self.size[1]) * w // self.size[1]).clip(0, w - 1)
            return a[ys][:, xs]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        if self.padding:
            pads = [(self.padding, self.padding), (self.padding, self.padding)] + [
                (0, 0)
            ] * (a.ndim - 2)
            a = np.pad(a, pads, mode="constant")
        h, w = a.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return a[i : i + th, j : j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return a[i : i + th, j : j + tw]
