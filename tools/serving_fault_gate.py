#!/usr/bin/env python
"""Serving fault-containment CI gate (run_tests.sh; skippable via
PADDLE_TPU_SKIP_FAULT_GATE=1).

In the crash/lint/serving-gate mold: a fast, deterministic proof that the
engine CONTAINS faults instead of dying or corrupting state.  Six
scenarios on a tiny CPU model, each asserting the PR's acceptance
criteria:

  1. transient step-crash  -> retry-once absorbs it: nothing fails, every
                              request token-for-token equal to the
                              unfaulted refs, zero retraces;
  2. persistent step-crash -> only the seated (implicated) requests end
                              FAILED with the typed error attached; the
                              queued remainder completes with parity;
  3. step-stall            -> the watchdog abandons the wedged worker,
                              rebuilds the pool, and keeps serving;
  4. NaN logits            -> the fused finiteness sentry quarantines
                              exactly the poisoned slot;
  5. pool exhaustion       -> injected allocator exhaustion backpressures
                              (never fails or corrupts), then drains;
  6. shared-prefix kill    -> two requests share a prefix-cache page; the
                              hitting one is killed mid-decode (stall ->
                              rebuild).  The rebuild flushes the cache
                              (its pages lived in the discarded pool),
                              the queued survivor completes token-for-
                              token against the rebuilt pool, and shared-
                              page refcounts stay exact throughout;

plus a RANDOMIZED fault schedule sweep (several seeds): under any mix of
crashes/NaN/exhaustion/callback faults, page accounting must close
exactly — occupancy never exceeds capacity, zero pages in use at drain,
free list whole — every request must reach a typed terminal state, and
every DONE request must match the unfaulted run.

Exit codes: 0 ok, 1 containment violated.
"""
from __future__ import annotations

import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np  # noqa: E402

N_NEW = 4


def _build():
    import paddle_tpu as pt
    from paddle_tpu.models import GPTForPretraining, gpt_tiny

    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (s,))
               for s in (5, 9, 7, 12, 17, 4, 11, 6)]
    refs = [np.asarray(
        m.generate(pt.to_tensor(p[None, :], dtype="int64"),
                   max_new_tokens=N_NEW, max_seq_len=64,
                   cache_dtype="float32").numpy())[0]
        for p in prompts]
    return m, prompts, refs


def _engine(m, **kw):
    from paddle_tpu.serving import ServingEngine

    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_context", 64)
    kw.setdefault("cache_dtype", "float32")
    return ServingEngine(m, **kw)


def _drain(eng, max_steps=2000):
    steps = 0
    while eng.queue.depth or eng.scheduler.active_slots:
        met = eng.step()
        steps += 1
        if met["pages_used"] > eng.allocator.capacity:
            raise AssertionError(
                f"pool over capacity: {met['pages_used']}")
        if steps >= max_steps:
            raise AssertionError("engine stopped making progress")
        if not met["active_slots"] and not met["tokens_this_step"]:
            time.sleep(0.001)
    return steps


def _accounting_closed(eng, label):
    """Exact page accounting at drain: no slot holds pages, the 4-term
    ledger closes (free + used + spec + shared == capacity is the
    allocator invariant; at drain used == spec == 0), and every page the
    prefix cache retained is at refcount 0 (no slot is referencing it)."""
    a = eng.allocator
    if a.used_pages != 0 or a.spec_pages != 0 \
            or a.free_pages + a.shared_pages != a.capacity:
        print(f"serving_fault_gate: FAIL [{label}] page accounting leaked "
              f"(used={a.used_pages}, spec={a.spec_pages}, "
              f"free={a.free_pages}, shared={a.shared_pages}, "
              f"capacity={a.capacity})")
        return False
    held = {p: c for p, c in getattr(a, "_shared", {}).items() if c}
    if held:
        print(f"serving_fault_gate: FAIL [{label}] shared pages still "
              f"referenced at drain: {held}")
        return False
    return True


def _done_parity(reqs, refs, label):
    from paddle_tpu.serving import RequestState

    bad = 0
    for r, ref in zip(reqs, refs):
        if r.state == RequestState.DONE and not np.array_equal(
                r.output_ids(), ref):
            bad += 1
    if bad:
        print(f"serving_fault_gate: FAIL [{label}] {bad} surviving "
              "request(s) diverged from the unfaulted run")
    return bad == 0


def gate() -> int:
    from paddle_tpu import serving
    from paddle_tpu.serving import (
        FaultInjector, NaNLogitsError, RequestState, StepStalledError,
        random_schedule,
    )

    m, prompts, refs = _build()
    ok = True

    # -- 1. transient crash: retry absorbs it ----------------------------
    serving.reset_serve_trace_counts()
    eng = _engine(m)
    inj = FaultInjector().inject("before_decode", at=2,
                                 kind="step_exception").install(eng)
    reqs = [eng.submit(p, N_NEW) for p in prompts[:4]]
    _drain(eng)
    mt = eng.metrics()
    tc = serving.serve_trace_counts()
    if not (inj.fired() == 1 and mt["step_retries"] == 1
            and mt["failed"] == 0 and mt["recoveries"] == 0
            and all(r.state == RequestState.DONE for r in reqs)
            and all(np.array_equal(r.output_ids(), ref)
                    for r, ref in zip(reqs, refs))
            and tc["fused"] <= 2):
        print(f"serving_fault_gate: FAIL [transient] {mt} traces={tc} "
              f"states={[r.state for r in reqs]}")
        ok = False
    ok &= _accounting_closed(eng, "transient")
    eng.close()

    # -- 2. persistent crash: only the implicated fail -------------------
    eng = _engine(m)
    FaultInjector().inject("before_decode", at=1, times=2,
                           kind="step_exception").install(eng)
    reqs = [eng.submit(p, N_NEW) for p in prompts[:4]]
    _drain(eng)
    mt = eng.metrics()
    failed = [r for r in reqs if r.state == RequestState.FAILED]
    done = [r for r in reqs if r.state == RequestState.DONE]
    if not (mt["recoveries"] == 1 and len(failed) == 2 and len(done) == 2
            and all(r.error is not None for r in failed)):
        print(f"serving_fault_gate: FAIL [persistent] {mt} "
              f"states={[r.state for r in reqs]}")
        ok = False
    ok &= _done_parity(reqs, refs, "persistent")
    ok &= _accounting_closed(eng, "persistent")
    eng.close()

    # -- 3. stall: watchdog abandons + rebuilds --------------------------
    eng = _engine(m, stall_budget_s=0.5)
    warm = eng.submit(prompts[0], 2)
    _drain(eng)                                  # compile under the big budget
    assert warm.finished
    FaultInjector().inject("before_decode", at=0, kind="step_stall",
                           duration=1.5).install(eng)
    reqs = [eng.submit(p, N_NEW) for p in prompts[:4]]
    _drain(eng)
    mt = eng.metrics()
    stalled = [r for r in reqs if isinstance(r.error, StepStalledError)]
    done = [r for r in reqs if r.state == RequestState.DONE]
    if not (mt["recoveries"] == 1 and mt["rebuilds"] == 1
            and len(stalled) == 2 and len(done) == 2):
        print(f"serving_fault_gate: FAIL [stall] {mt} "
              f"states={[r.state for r in reqs]}")
        ok = False
    ok &= _done_parity(reqs, refs, "stall")
    ok &= _accounting_closed(eng, "stall")
    eng.close()

    # -- 4. NaN logits: sentry quarantines the poisoned slot only --------
    eng = _engine(m)
    FaultInjector().inject("after_decode", at=1, kind="nan_logits",
                           slots=[0]).install(eng)
    reqs = [eng.submit(p, N_NEW) for p in prompts[:4]]
    _drain(eng)
    mt = eng.metrics()
    poisoned = [r for r in reqs if isinstance(r.error, NaNLogitsError)]
    done = [r for r in reqs if r.state == RequestState.DONE]
    if not (mt["quarantined"] == 1 and len(poisoned) == 1
            and len(done) == 3):
        print(f"serving_fault_gate: FAIL [nan] {mt} "
              f"states={[r.state for r in reqs]}")
        ok = False
    ok &= _done_parity(reqs, refs, "nan")
    ok &= _accounting_closed(eng, "nan")
    eng.close()

    # -- 5. pool exhaustion: backpressure, never corruption --------------
    eng = _engine(m)
    FaultInjector().inject("alloc", at=0, times=4,
                           kind="alloc_exhausted").install(eng)
    reqs = [eng.submit(p, N_NEW) for p in prompts[:4]]
    _drain(eng)
    if not all(r.state == RequestState.DONE
               and np.array_equal(r.output_ids(), ref)
               for r, ref in zip(reqs, refs)):
        print("serving_fault_gate: FAIL [exhaustion] "
              f"states={[r.state for r in reqs]}")
        ok = False
    ok &= _accounting_closed(eng, "exhaustion")
    eng.close()

    # -- 6. shared prefix killed mid-decode: survivor + refcounts exact --
    # Two requests share a cached prefix through the prefix cache
    # (docs/serving.md "Prefix cache"); the one that hit is killed
    # mid-decode by a stall.  The rebuild flushes the cache (its pages
    # lived in the discarded pool), the survivor — queued behind it on
    # the single slot — is admitted against the rebuilt pool and must
    # come out token-for-token; shared-page refcounts must be exact at
    # every stage (held while seated, zero after the flush and at drain).
    from paddle_tpu.serving import ServingEngine

    prng = np.random.RandomState(9)
    vocab = m.config.vocab_size
    shared = prng.randint(0, vocab, (20,))       # 1 full page + tail
    tail_b = prng.randint(0, vocab, (5,))
    eng = ServingEngine(m, num_slots=1, page_size=16, max_context=64,
                        cache_dtype="float32", stall_budget_s=0.5,
                        prefix_cache=True)
    warm = eng.submit(prompts[0], 2)
    _drain(eng)                                  # compile under the big budget
    assert warm.finished
    ra = eng.submit(shared, N_NEW)               # registers the prefix page
    _drain(eng)
    ref_a = ra.output_ids()
    if not (ra.state == RequestState.DONE
            and eng.allocator.shared_pages >= 1):
        print("serving_fault_gate: FAIL [prefix] seeding request did not "
              f"register a shared page (state={ra.state}, "
              f"shared={eng.allocator.shared_pages})")
        ok = False
    FaultInjector().inject("before_decode", at=0, kind="step_stall",
                           duration=1.5).install(eng)
    rb = eng.submit(np.concatenate([shared, tail_b]), N_NEW)  # cache hit
    rc = eng.submit(shared, N_NEW)               # queued survivor (1 slot)
    _drain(eng)
    mt = eng.metrics()
    if not (isinstance(rb.error, StepStalledError)
            and rb.state == RequestState.FAILED
            and mt["rebuilds"] == 1
            and mt["prefix_hits"] >= 1
            and mt["prefix_evictions"] >= 1       # the rebuild flush
            and rc.state == RequestState.DONE
            and np.array_equal(rc.output_ids(), ref_a)):
        print(f"serving_fault_gate: FAIL [prefix] {mt} "
              f"states={[rb.state, rc.state]} err={rb.error!r}")
        ok = False
    # the survivor completed AFTER the flush, so it re-registered the
    # prefix into the rebuilt pool: the cache is warm again, refcount 0
    if eng.allocator.shared_pages < 1:
        print("serving_fault_gate: FAIL [prefix] survivor did not "
              "re-register the prefix after the rebuild flush")
        ok = False
    ok &= _accounting_closed(eng, "prefix")
    eng.close()

    # -- 7. randomized schedules: the accounting property ----------------
    for seed in (3, 17, 42):
        rng = np.random.RandomState(seed)
        eng = _engine(m, num_slots=3)
        random_schedule(rng, horizon=25, n_faults=4, num_slots=3).install(eng)
        reqs = [eng.submit(p, N_NEW) for p in prompts]
        try:
            _drain(eng)
        except AssertionError as e:
            print(f"serving_fault_gate: FAIL [random seed={seed}] {e}")
            ok = False
            eng.close()
            continue
        if not all(r.terminal for r in reqs):
            print(f"serving_fault_gate: FAIL [random seed={seed}] "
                  "non-terminal request after drain")
            ok = False
        if any(r.state != RequestState.DONE and r.error is None
               for r in reqs):
            print(f"serving_fault_gate: FAIL [random seed={seed}] "
                  "non-DONE terminal without a typed error")
            ok = False
        ok &= _done_parity(reqs, refs, f"random seed={seed}")
        ok &= _accounting_closed(eng, f"random seed={seed}")
        eng.close()

    if not ok:
        return 1
    print("serving_fault_gate: OK (transient-retry, persistent-crash, "
          "stall-rebuild, nan-quarantine, exhaustion-backpressure, "
          "shared-prefix-kill, 3 randomized schedules — containment + "
          "exact page accounting incl. shared pages)")
    return 0


if __name__ == "__main__":
    sys.exit(gate())
