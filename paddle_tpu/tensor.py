"""The Tensor type.

TPU-native equivalent of the reference's eager Tensor
(reference: paddle/phi/api/include/tensor.h:82 C++ ``paddle::Tensor``; python
surface monkeypatched in python/paddle/fluid/dygraph/tensor_patch_methods.py
and pybind paddle/fluid/pybind/eager_method.cc).

A Tensor wraps a ``jax.Array`` (or, during jit tracing, a jax tracer) plus
autograd metadata — the analog of AutogradMeta
(paddle/fluid/eager/autograd_meta.h:61). Device memory, layout, and async
execution are owned by XLA/PJRT — there is no user-visible stream or
allocator, matching TPU's runtime-managed HBM model.

Most op-methods (``Tensor.add`` …) are attached by ``paddle_tpu.ops`` at
import time, mirroring the reference's math_op_patch approach.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import dtype as _dtype_mod
from .core.dtype import DType, convert_dtype, to_jax_dtype
from .core.place import Place, current_place

__all__ = ["Tensor", "Parameter", "to_tensor"]

# creation-generation counter: jit.to_static bumps this before its scout run
# so the capture logger can tell pre-existing state (params, buffers, RNG
# keys) apart from tensors created during the traced call.
_GENERATION = [0]

# Abstract-scout bookkeeping (jit.to_static's zero-compute capture pass, see
# paddle_tpu/jit/api.py): while active, every Tensor creation is logged with
# its initial raw value, and every ``_set_value`` records the pre-mutation
# value once.  This lets the scout restore ALL python-visible state after
# tracing under jax.eval_shape — no eager warmup step (and no eager-step HBM
# residency) is ever needed.  Thread-local (like dispatch._TraceState): a
# concurrent thread's tensor writes must not be captured — or rolled back —
# by another thread's scout.
import threading as _threading


class _ScoutState(_threading.local):
    def __init__(self):
        self.creation_log = None
        self.orig_values = None
        self.orig_grads = None


_SCOUT_STATE = _ScoutState()


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_output_index",
        "_hooks",
        "_next_hook_id",
        "_gen",
        "name",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad: Optional["Tensor"] = None
        self._grad_node = None
        self._output_index = 0
        self._hooks = {}
        self._next_hook_id = 0
        self._gen = _GENERATION[0]
        self.name = name
        _cl = _SCOUT_STATE.creation_log
        if _cl is not None:
            _cl[id(self)] = (self, value)

    # -- raw value plumbing ------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g: Optional["Tensor"]):
        # abstract-scout bookkeeping: record the PRE-trace grad binding once
        # so the scout can restore it exactly (a param's accumulated eager
        # grad must survive a zero-side-effect capture pass)
        _og = _SCOUT_STATE.orig_grads
        if _og is not None and id(self) not in _og:
            _og[id(self)] = (self, self._grad)
        self._grad = g

    def _set_value(self, raw):
        """Rebind the underlying array (in-place update semantics).

        Under jit.to_static tracing this mutation is logged so the trace can
        functionalize it (return the new value as a program output)."""
        from .ops import dispatch as _dispatch

        _ov = _SCOUT_STATE.orig_values
        if _ov is not None and id(self) not in _ov:
            # (tensor, pre-mutation value): keyed off the raw _set_value hook
            # rather than the jit mutation log, because nested tracing scopes
            # (static.nn.cond branch functionalization) swap the mutation log
            # out — the scout must still restore those tensors afterwards.
            _ov[id(self)] = (self, self._value)
        self._value = raw
        log = _dispatch._trace_state.mutation_log
        if log is not None:
            log[id(self)] = self

    def set_value(self, value):
        """Public in-place assignment (reference Tensor.set_value):
        accepts Tensor / ndarray / scalar, preserving this tensor's dtype."""
        import numpy as _np

        raw = value._value if isinstance(value, Tensor) else value
        raw = jnp.asarray(_np.asarray(raw), dtype=self._value.dtype)
        if tuple(raw.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {list(raw.shape)} vs "
                f"{self.shape}")
        self._set_value(raw)

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self) -> DType:
        return convert_dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        devs = getattr(self._value, "devices", None)
        if callable(devs):
            try:
                dev = next(iter(self._value.devices()))
                backend = "cpu" if dev.platform == "cpu" else "tpu"
                return Place(backend, dev.id)
            except Exception:
                pass
        return current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self):
        from . import ops

        return ops.creation.to_tensor(self.size, dtype="int64")

    # -- conversion --------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype):
        from .ops import dispatch

        jd = to_jax_dtype(dtype)
        return dispatch.apply(lambda x: x.astype(jd), self, op_name="cast")

    cast = astype

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def clone(self) -> "Tensor":
        from .ops import dispatch

        return dispatch.apply(lambda x: x + 0, self, op_name="clone")

    def to(self, device=None, dtype=None, blocking=None):
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from .core.place import set_device, current_place

            place = device if isinstance(device, Place) else None
            if place is None:
                backend = device.split(":")[0]
                idx = int(device.split(":")[1]) if ":" in device else 0
                if backend in ("gpu", "xpu", "npu"):
                    backend = "tpu"
                place = Place(backend, idx)
            dev = place.device
            if dev is not None:
                raw = jax.device_put(out._value, dev)
                t = Tensor(raw, stop_gradient=out.stop_gradient, name=out.name)
                t._grad_node = out._grad_node
                t._output_index = out._output_index
                return t
        return out

    def cpu(self):
        return self.to("cpu")

    def cuda(self, *a, **k):  # reference-API compat: accelerator == TPU here
        return self.to("tpu")

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd.engine import run_backward

        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        hid = self._next_hook_id
        self._next_hook_id += 1
        self._hooks[hid] = hook

        class _Handle:
            def remove(_self):
                self._hooks.pop(hid, None)

        return _Handle()

    @property
    def persistable(self):
        return isinstance(self, Parameter)

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        from .ops import dispatch

        idx = _unwrap_index(idx)
        return dispatch.apply(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = value._value if isinstance(value, Tensor) else value
        self._set_value(self._value.at[idx].set(v))

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            data = np.asarray(self._value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={sg},\n       {body})"
        )

    def __bool__(self):
        import jax as _jax

        if isinstance(self._value, _jax.core.Tracer):
            # trace-unstable branching: `if tensor:` / `while tensor:` on a
            # value only known at run time cannot compile (reference
            # dy2static rewrites these into cond/while ops via AST
            # transforms — program_translator.py)
            raise RuntimeError(
                "data-dependent Python control flow on a traced Tensor: "
                "`if`/`while` on a runtime value cannot be compiled by "
                "jit.to_static. Use paddle_tpu.static.nn.cond(pred, "
                "true_fn, false_fn) or paddle_tpu.static.nn.while_loop "
                "instead (they lower to lax.cond / lax.while_loop inside "
                "the compiled program)."
            )
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)

    # operator overloads are installed by paddle_tpu.ops (math_op_patch analog)


class Parameter(Tensor):
    """A trainable Tensor owned by a Layer (reference:
    python/paddle/fluid/framework.py Parameter / EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor analog (reference: python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        raw = data._value
        if dtype is not None:
            raw = raw.astype(to_jax_dtype(dtype))
        t = Tensor(raw, stop_gradient=stop_gradient)
        return t
    if dtype is None:
        if isinstance(data, (bool, np.bool_)):
            jd = np.bool_
        elif isinstance(data, (int, np.integer)):
            jd = np.int64
        elif isinstance(data, (float, np.floating)):
            jd = np.float32
        elif isinstance(data, np.ndarray):
            jd = data.dtype  # numpy arrays keep their dtype, like the reference
        elif isinstance(data, (list, tuple)):
            # python literals: default float dtype is float32 (reference
            # paddle.get_default_dtype()); ints stay int64, bools bool
            arr = np.asarray(data)
            jd = np.float32 if arr.dtype == np.float64 else arr.dtype
            data = arr
        else:
            jd = None
        raw = jnp.asarray(data, dtype=jd)
    else:
        raw = jnp.asarray(data, dtype=to_jax_dtype(dtype))
    if place is not None:
        dev = place.device if isinstance(place, Place) else None
        if dev is not None:
            raw = jax.device_put(raw, dev)
    return Tensor(raw, stop_gradient=stop_gradient)
