"""AST dy2static: native python ``if``/``while`` over traced Tensors.

Reference: python/paddle/jit/dy2static/ast_transformer.py (DygraphToStaticAst
rewrites IfElse/While/For into conditional_block / while ops) +
program_translator.py:305 (StaticFunction applies the transform before
tracing).

TPU-native redesign: instead of rewriting into ProgramDesc ops, each native
``if``/``while`` is rewritten into a RUNTIME-DISPATCHED site:

* predicate is a concrete python value / eager Tensor -> the ORIGINAL python
  control flow runs, preserving dygraph semantics bit-for-bit (including
  ``break``/``continue``/side effects);
* predicate is a traced Tensor (inside ``jit.to_static``'s capture or
  compile trace) -> the site lowers through ``static.nn.cond`` /
  ``static.nn.while_loop`` onto ``lax.cond`` / ``lax.while_loop`` inside
  the SAME compiled program.

A site whose shape can't be functionalized (early return out of one branch
only, ``break`` in a tensor-predicate loop, attribute mutation inside a
branch) keeps its python path and raises a clear error NAMING THE SOURCE
LINE only if the predicate actually turns out to be traced.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional, Set, Tuple

import jax
import numpy as np

from ...tensor import Tensor

__all__ = ["convert_to_static", "Dy2StaticUnsupported"]


class Dy2StaticUnsupported(RuntimeError):
    """A tensor-dependent control-flow site could not be functionalized."""


# -- runtime helpers (referenced by transformed code as __pt_d2s.*) --------

class _Missing:
    def __repr__(self):
        return "<dy2static: name undefined before this control-flow site>"


_MISSING = _Missing()


def _get(f):
    """Evaluate a deferred name lookup, tolerating not-yet-bound names
    (python defines them inside the branch/loop; the seeded default is then
    never read)."""
    try:
        return f()
    except NameError:
        return _MISSING


def _is_traced_pred(p) -> bool:
    return isinstance(p, Tensor) and isinstance(p._value, jax.core.Tracer)


def run_cond(pred, true_fn, false_fn):
    from ...static import nn as static_nn

    def _checked(fn):
        def wrapper():
            out = fn()
            flat = out if isinstance(out, tuple) else (out,)
            if any(o is _MISSING for o in flat):
                raise Dy2StaticUnsupported(
                    "dy2static: a variable is assigned in only one branch "
                    "of a tensor `if` and undefined before it — both "
                    "branches of a traced conditional must produce every "
                    "output (initialize the variable before the if)")
            return out
        return wrapper

    return static_nn.cond(pred, _checked(true_fn), _checked(false_fn))


def reraise_unsupported(e, lineno, reason):
    """Convert Tensor.__bool__'s generic trace error (raised from an
    untransformable loop that actually hit a traced predicate) into the
    precise dy2static error naming the source line."""
    if "data-dependent Python control flow" in str(e):
        unsupported(lineno, reason)
    raise e


def run_while(cond_fn, body_fn, vals, max_iter=None):
    from ...static import nn as static_nn

    if any(v is _MISSING for v in vals):
        raise Dy2StaticUnsupported(
            "dy2static: a loop variable is undefined before a "
            "tensor-predicate while loop; initialize it first")
    out = static_nn.while_loop(cond_fn, body_fn, list(vals),
                               max_iter=max_iter)
    return tuple(out)


def unsupported(lineno, reason):
    raise Dy2StaticUnsupported(
        f"dy2static: tensor-dependent control flow at source line {lineno} "
        f"cannot be functionalized: {reason}. Restructure with "
        "paddle_tpu.static.nn.cond / while_loop, or keep the predicate "
        "un-traced.")


# -- for-loop helpers (reference jit/dy2static/loop_transformer.py:
#    For -> While conversion over range/iterable forms) -------------------

_builtin_range = range


def normalize_range(args):
    """range(stop) / range(start, stop[, step]) -> (start, stop, step);
    each may be a python int or a (possibly traced) scalar Tensor."""
    if len(args) == 1:
        out = (0, args[0], 1)
    elif len(args) == 2:
        out = (args[0], args[1], 1)
    else:
        out = (args[0], args[1], args[2])
    step = out[2]
    if isinstance(step, Tensor) and not _is_traced_pred(step):
        step = int(np.asarray(step._value))
    if isinstance(step, int) and step == 0:
        raise ValueError("range() arg 3 must not be zero")
    return out


def seed_target(getter, start, step):
    """Initial carry for the loop variable: its PRIOR binding when one
    exists (python leaves the target untouched on a zero-trip range),
    else the would-be first value.  The prior value is cast to the loop
    value's dtype — lax.while_loop requires a type-stable carry."""
    import jax.numpy as jnp

    first = range_value(start, step, 0)
    v = _get(getter)
    if v is _MISSING:
        return first
    return Tensor(jnp.asarray(_raw(v)).astype(first._value.dtype))


def any_traced(*vals) -> bool:
    return any(_is_traced_pred(v) if isinstance(v, Tensor) else False
               for v in vals)


def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def range_trip_count(start, stop, step):
    """Trip count of range(start, stop, step) as a device value:
    max(0, ceil((stop-start)/step)) via the floor-div identity
    ceil(a/b) == -((-a)//b) (works for negative steps too)."""
    import jax.numpy as jnp

    s, e, st = _raw(start), _raw(stop), _raw(step)
    n = -((s - e) // st)
    return Tensor(jnp.maximum(jnp.asarray(n), 0))


def range_value(start, step, i):
    """The loop variable's value at iteration i (traced arithmetic)."""
    import jax.numpy as jnp

    return Tensor(jnp.asarray(_raw(start)) + jnp.asarray(_raw(i))
                  * jnp.asarray(_raw(step)))


def int_tensor(v: int) -> Tensor:
    # default integer dtype (int64 under the repo's x64 regime) so the
    # counter, range_value and seed_target carries all agree
    import jax.numpy as jnp

    return Tensor(jnp.asarray(v))


# -- AST analysis ----------------------------------------------------------

def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    """Simple-Name binding targets in a statement list (recursing into
    nested compound statements but NOT into nested function/class defs)."""
    names: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # don't descend
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        def _target(self, t):
            if isinstance(t, ast.Name):
                if not t.id.startswith("__pt_"):  # synthetic temps stay local
                    names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)
            elif isinstance(t, ast.Starred):
                self._target(t.value)

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.value is not None:
                self._target(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_withitem(self, node):
            if node.optional_vars is not None:
                self._target(node.optional_vars)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _has_node(stmts: List[ast.stmt], kinds, stop_at_loops=False) -> bool:
    """Does any statement contain a node of the given kinds (not descending
    into nested defs; optionally not into nested loops for break/continue
    ownership)?"""
    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        def visit_For(self, node):
            if stop_at_loops:
                # break/continue inside a NESTED loop belong to it
                self.visit(node.iter)
                return
            self.generic_visit(node)

        def visit_While(self, node):
            if stop_at_loops:
                self.visit(node.test)
                return
            self.generic_visit(node)

        def generic_visit(self, node):
            if isinstance(node, kinds):
                self.found = True
                return
            super().generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _non_name_bindings(stmts: List[ast.stmt]) -> bool:
    """Attribute/Subscript assignment targets (python-object mutation a
    traced branch cannot functionalize)."""
    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        def _target(self, t):
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                self.found = True
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _trailing_return(stmts: List[ast.stmt]):
    """(stmts_without_trailing_return, return_expr | None)."""
    if stmts and isinstance(stmts[-1], ast.Return):
        ret = stmts[-1].value
        return stmts[:-1], (ret if ret is not None
                            else ast.Constant(value=None))
    return stmts, None


def _src(stmts: List[ast.stmt], indent: str) -> str:
    if not stmts:
        return f"{indent}pass"
    body = ast.unparse(ast.Module(body=stmts, type_ignores=[]))
    return textwrap.indent(body, indent)


def _ends_in_return(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _normalize_early_returns(stmts: List[ast.stmt],
                             at_function_top: bool) -> List[ast.stmt]:
    """Fold the early-return idiom into if/else so it functionalizes:

        if c: return A          if c: return A
        <rest>           ->     else: <rest>

    Applied recursively to nested compound bodies.  At function top level
    an early-return `if` that is the LAST statement gains an explicit
    `else: return None` (python's implicit fallthrough)."""
    out: List[ast.stmt] = []
    for i, s in enumerate(stmts):
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(s, field, None)
            if (sub and not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef))):
                setattr(s, field, _normalize_early_returns(sub, False))
        if (isinstance(s, ast.If) and not s.orelse
                and _ends_in_return(s.body)):
            rest = _normalize_early_returns(stmts[i + 1:], at_function_top)
            if rest:
                s.orelse = rest
                out.append(s)
                return out
            if at_function_top:
                s.orelse = [ast.Return(value=ast.Constant(value=None))]
        out.append(s)
    return out


# -- the transformer -------------------------------------------------------

class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False

    def _n(self) -> int:
        self.counter += 1
        return self.counter

    # nested defs keep their own control flow untouched (they are traced
    # as closures; converting them requires their own convert_to_static)
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        n = self._n()
        lineno = getattr(node, "lineno", 0)
        body, orelse = node.body, node.orelse
        test_src = ast.unparse(node.test)

        py_arm = (f"if __pt_p{n}:\n{_src(body, '    ')}\n"
                  + (f"else:\n{_src(orelse, '    ')}" if orelse else ""))

        reason = None
        if _non_name_bindings(body) or _non_name_bindings(orelse):
            reason = ("a branch assigns to an attribute/subscript "
                      "(python-object mutation)")
        elif _has_node(body + orelse, (ast.Break, ast.Continue),
                       stop_at_loops=True):
            reason = "a branch breaks/continues an enclosing loop"

        body2, ret_t = _trailing_return(body)
        orelse2, ret_f = _trailing_return(orelse)
        has_inner_ret = _has_node(body2 + orelse2, (ast.Return,))

        if reason is None and has_inner_ret:
            reason = "a branch returns from the middle of its body"
        elif reason is None and (ret_t is None) != (ret_f is None):
            reason = ("one branch returns and the other falls through "
                      "(make both return, or neither)")

        if reason is not None:
            block = (
                f"__pt_p{n} = {test_src}\n"
                f"if __pt_d2s._is_traced_pred(__pt_p{n}):\n"
                f"    __pt_d2s.unsupported({lineno}, {reason!r})\n"
                f"{py_arm}"
            )
            self.changed = True
            return ast.parse(block).body

        if ret_t is not None:
            # both branches return: the traced arm returns cond(...).
            # Helper params seed branch-local names from enclosing scope so
            # read-then-assign patterns (`x = x + 1`) resolve like the
            # original code did.
            assigned = sorted(_assigned_names(body2) | _assigned_names(orelse2))
            seeds = ", ".join(
                f"{v}=__pt_d2s._get(lambda: {v})" for v in assigned)
            block = (
                f"__pt_p{n} = {test_src}\n"
                f"def __pt_t{n}({seeds}):\n{_src(body2, '    ')}\n"
                f"    return {ast.unparse(ret_t)}\n"
                f"def __pt_f{n}({seeds}):\n{_src(orelse2, '    ')}\n"
                f"    return {ast.unparse(ret_f)}\n"
                f"if __pt_d2s._is_traced_pred(__pt_p{n}):\n"
                f"    return __pt_d2s.run_cond(__pt_p{n}, __pt_t{n}, __pt_f{n})\n"
                f"else:\n"
                + textwrap.indent(py_arm, "    ")
            )
            self.changed = True
            return ast.parse(block).body

        assigned = sorted(_assigned_names(body) | _assigned_names(orelse))
        if not assigned:
            block = (
                f"__pt_p{n} = {test_src}\n"
                f"if __pt_d2s._is_traced_pred(__pt_p{n}):\n"
                f"    __pt_d2s.unsupported({lineno}, "
                f"'branches bind no variables and return nothing "
                f"(side-effect-only branch)')\n"
                f"{py_arm}"
            )
            self.changed = True
            return ast.parse(block).body

        vars_tuple = ", ".join(assigned)
        seeds = ", ".join(f"{v}=__pt_d2s._get(lambda: {v})" for v in assigned)
        block = (
            f"__pt_p{n} = {test_src}\n"
            f"def __pt_t{n}({seeds}):\n{_src(body, '    ')}\n"
            f"    return ({vars_tuple},)\n"
            f"def __pt_f{n}({seeds}):\n{_src(orelse, '    ')}\n"
            f"    return ({vars_tuple},)\n"
            f"if __pt_d2s._is_traced_pred(__pt_p{n}):\n"
            f"    ({vars_tuple},) = __pt_d2s.run_cond("
            f"__pt_p{n}, __pt_t{n}, __pt_f{n})\n"
            f"else:\n"
            + textwrap.indent(py_arm, "    ")
        )
        self.changed = True
        return ast.parse(block).body

    def visit_For(self, node: ast.For):
        """For -> bounded-while conversion (reference
        loop_transformer.py For handling).  Two rewritten shapes:

        - ``for v in range(...)`` with a TRACED bound: the counter/value
          arithmetic moves into the while machinery (lax-compatible);
          python bounds keep the original python loop.
        - ``for v in <tensor>``: iterate indices pythonly (the length is
          static under tracing, so the unrolled loop is a valid trace).

        Anything else (python iterables) is left untouched."""
        self.generic_visit(node)
        n = self._n()
        lineno = getattr(node, "lineno", 0)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and 1 <= len(node.iter.args) <= 3
                    and not any(isinstance(a, ast.Starred)
                                for a in node.iter.args))
        py_arm = (f"for {ast.unparse(node.target)} in __pt_itv{n}:\n"
                  f"{_src(node.body, '    ')}\n"
                  + (f"else:\n{_src(node.orelse, '    ')}"
                     if node.orelse else ""))

        if not is_range:
            # non-range iterables (incl. Tensors, which iterate via
            # Tensor.__iter__ with a static length) keep native python
            # control flow — a valid trace
            return node

        reason = None
        if not isinstance(node.target, ast.Name):
            reason = ("the loop target unpacks a tuple (use a single "
                      "name over range)")
        elif node.orelse:
            reason = "for/else is not supported for tensor bounds"
        elif _has_node(node.body, (ast.Break, ast.Continue),
                       stop_at_loops=True):
            reason = "break/continue in a tensor-bound for loop"
        elif _has_node(node.body, (ast.Return,)):
            reason = "return inside a tensor-bound for loop"
        elif _non_name_bindings(node.body):
            reason = ("the loop body assigns to an attribute/subscript "
                      "(python-object mutation)")

        args_src = ", ".join(ast.unparse(a) for a in node.iter.args)
        # `range` may be shadowed by a user function: capture whatever
        # the name resolves to and only engage the machinery for the
        # builtin (a shadowed range keeps its original call + python for)
        shadow_guard = (
            f"__pt_rng{n} = range\n"
            f"if __pt_rng{n} is not __pt_d2s._builtin_range:\n"
            f"    __pt_itv{n} = __pt_rng{n}({args_src})\n"
            + textwrap.indent(py_arm, "    ") + "\n"
            f"else:\n"
        )
        if reason is not None:
            inner = (
                f"__pt_ra{n} = ({args_src},)\n"
                f"__pt_s{n}, __pt_e{n}, __pt_st{n} = "
                f"__pt_d2s.normalize_range(__pt_ra{n})\n"
                f"if __pt_d2s.any_traced(__pt_s{n}, __pt_e{n}, "
                f"__pt_st{n}):\n"
                f"    __pt_d2s.unsupported({lineno}, {reason!r})\n"
                f"__pt_itv{n} = range(__pt_s{n}, __pt_e{n}, __pt_st{n})\n"
                + py_arm
            )
            self.changed = True
            return ast.parse(shadow_guard
                             + textwrap.indent(inner, "    ")).body

        tgt = node.target.id
        assigned = sorted(_assigned_names(node.body) - {tgt})
        vars_sig = ", ".join([f"__pt_i{n}", tgt] + assigned)
        inits = ", ".join(
            [f"__pt_d2s.int_tensor(0)",
             f"__pt_d2s.seed_target(lambda: {tgt}, __pt_s{n}, __pt_st{n})"]
            + [f"__pt_d2s._get(lambda: {v})" for v in assigned])
        ret_vars = ", ".join([f"__pt_i{n} + 1", tgt] + assigned)
        out_vars = ", ".join([f"__pt_i{n}", tgt] + assigned)
        inner = (
            f"__pt_ra{n} = ({args_src},)\n"
            f"__pt_s{n}, __pt_e{n}, __pt_st{n} = "
            f"__pt_d2s.normalize_range(__pt_ra{n})\n"
            f"if __pt_d2s.any_traced(__pt_s{n}, __pt_e{n}, __pt_st{n}):\n"
            f"    __pt_n{n} = __pt_d2s.range_trip_count("
            f"__pt_s{n}, __pt_e{n}, __pt_st{n})\n"
            f"    def __pt_fc{n}({vars_sig}):\n"
            f"        return __pt_i{n} < __pt_n{n}\n"
            f"    def __pt_fb{n}({vars_sig}):\n"
            f"        {tgt} = __pt_d2s.range_value("
            f"__pt_s{n}, __pt_st{n}, __pt_i{n})\n"
            f"{_src(node.body, '        ')}\n"
            f"        return ({ret_vars},)\n"
            f"    ({out_vars},) = __pt_d2s.run_while("
            f"__pt_fc{n}, __pt_fb{n}, ({inits},), "
            f"max_iter=__pt_d2s.DEFAULT_MAX_ITER)\n"
            f"else:\n"
            f"    __pt_itv{n} = range(__pt_s{n}, __pt_e{n}, __pt_st{n})\n"
            + textwrap.indent(py_arm, "    ")
        )
        self.changed = True
        return ast.parse(shadow_guard + textwrap.indent(inner, "    ")).body

    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        n = self._n()
        lineno = getattr(node, "lineno", 0)
        test_src = ast.unparse(node.test)
        py_arm = (f"while {test_src}:\n{_src(node.body, '    ')}\n"
                  + (f"else:\n{_src(node.orelse, '    ')}"
                     if node.orelse else ""))

        reason = None
        if node.orelse:
            reason = "while/else is not supported for tensor predicates"
        elif _has_node(node.body, (ast.Break, ast.Continue),
                       stop_at_loops=True):
            reason = "break/continue in a tensor-predicate loop"
        elif _has_node(node.body, (ast.Return,)):
            reason = "return inside a tensor-predicate loop"
        elif _non_name_bindings(node.body):
            reason = ("the loop body assigns to an attribute/subscript "
                      "(python-object mutation)")

        assigned = sorted(_assigned_names(node.body))
        if reason is None and not assigned:
            reason = "the loop body binds no variables"

        if reason is not None:
            # untransformable shape: keep the ORIGINAL loop untouched (no
            # extra predicate evaluation — it may have side effects); if it
            # actually hits a traced predicate, Tensor.__bool__ raises and
            # is converted into the precise source-line error
            block = (
                f"try:\n"
                + textwrap.indent(py_arm, "    ") + "\n"
                f"except RuntimeError as __pt_e{n}:\n"
                f"    __pt_d2s.reraise_unsupported(__pt_e{n}, {lineno}, "
                f"{reason!r})"
            )
            self.changed = True
            return ast.parse(block).body

        # supported shape (no break/continue/return): dispatch on the
        # PREDICATE value only — python-valued predicates keep python
        # control flow (traced loop VARS just unroll, a valid trace), and
        # the probe evaluation is REUSED as the loop's first test so the
        # predicate is never evaluated an extra time
        vars_tuple = ", ".join(assigned)
        inits = ", ".join(f"__pt_d2s._get(lambda: {v})" for v in assigned)
        block = (
            f"def __pt_wc{n}({vars_tuple}):\n    return {test_src}\n"
            f"def __pt_wb{n}({vars_tuple}):\n{_src(node.body, '    ')}\n"
            f"    return ({vars_tuple},)\n"
            f"__pt_c{n} = {test_src}\n"
            f"if __pt_d2s._is_traced_pred(__pt_c{n}):\n"
            f"    ({vars_tuple},) = __pt_d2s.run_while("
            f"__pt_wc{n}, __pt_wb{n}, ({inits},), "
            f"max_iter=__pt_d2s.DEFAULT_MAX_ITER)\n"
            f"else:\n"
            f"    while __pt_c{n}:\n"
            f"{_src(node.body, '        ')}\n"
            f"        __pt_c{n} = {test_src}"
        )
        self.changed = True
        return ast.parse(block).body


# tensor-predicate `while` under a DIFFERENTIATED trace needs a static trip
# bound (lax.scan); None -> lax.while_loop (forward-only).  Users set this
# via paddle_tpu.jit.dy2static.set_default_max_iter(N).
DEFAULT_MAX_ITER: Optional[int] = None


def set_default_max_iter(n: Optional[int]):
    global DEFAULT_MAX_ITER
    DEFAULT_MAX_ITER = n


# -- entry point -----------------------------------------------------------

def convert_to_static(fn):
    """Return ``fn`` with native if/while rewritten for trace dispatch, or
    ``fn`` unchanged when it has no control flow / no retrievable source.

    The transform is semantics-preserving for python-valued predicates (the
    original control flow runs); only traced-Tensor predicates divert into
    static.nn.cond / while_loop."""
    if inspect.ismethod(fn):
        import types

        converted = convert_to_static(fn.__func__)
        if converted is fn.__func__:
            return fn
        return types.MethodType(converted, fn.__self__)
    if not inspect.isfunction(fn):
        return fn
    if fn.__name__ == "<lambda>":
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return fn
    if not any(isinstance(n, (ast.If, ast.While, ast.For))
               for n in ast.walk(fdef)):
        return fn
    if any(isinstance(n, (ast.Global, ast.Nonlocal)) for n in ast.walk(fdef)):
        return fn  # name-scope rewrites would break global/nonlocal decls

    tr = _ControlFlowTransformer()
    fdef.decorator_list = []
    fdef.body = _normalize_early_returns(fdef.body, at_function_top=True)
    # visit the BODY, not the def itself — visit_FunctionDef is the guard
    # that keeps nested defs untouched and would skip the whole function
    new_body: List[ast.stmt] = []
    for s in fdef.body:
        r = tr.visit(s)
        if isinstance(r, list):
            new_body.extend(r)
        elif r is not None:
            new_body.append(r)
    fdef.body = new_body
    new_fdef = fdef
    if not tr.changed:
        return fn
    ast.fix_missing_locations(new_fdef)

    freevars = fn.__code__.co_freevars
    inner = ast.unparse(new_fdef)
    factory_src = (
        f"def __pt_factory({', '.join(freevars)}):\n"
        + textwrap.indent(inner, "    ")
        + f"\n    return {fn.__name__}"
    )
    # exec with fn's REAL globals mapping (not a snapshot) so helpers
    # defined after the decorated function — and later reassignments of
    # module globals — resolve exactly like they do in the original.
    # `__pt_d2s` is installed once per module; `__pt_factory` is removed.
    import sys as _sys
    ns = fn.__globals__
    ns["__pt_d2s"] = _sys.modules[__name__]
    try:
        exec(compile(factory_src, f"<dy2static {fn.__name__}>", "exec"), ns)
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = ns.pop("__pt_factory")(*cells)
    except Exception:
        ns.pop("__pt_factory", None)
        return fn
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__doc__ = fn.__doc__
    new_fn.__module__ = fn.__module__
    new_fn.__qualname__ = fn.__qualname__
    new_fn.__dict__.update(fn.__dict__)
    new_fn.__wrapped__ = fn
    return new_fn
