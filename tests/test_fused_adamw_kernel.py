"""Parity tests for the fused multi-tensor AdamW Pallas kernel
(ops/pallas_kernels/fused_adamw.py) in interpret mode, against the same
update math the XLA-composed path in optimizer/optimizers.py uses.

Reference: paddle/phi/kernels/fusion/fused_adam_kernel.cu semantics
(standard AdamW with decoupled weight decay and bias correction).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.pallas_kernels.fused_adamw import fused_adamw_update

B1, B2, EPS, WD = 0.9, 0.999, 1e-8, 0.01


def _composed(p, g, m1, m2, lr, b1p, b2p):
    p32 = p.astype(np.float32)
    g32 = g.astype(np.float32)
    new_m1 = B1 * m1.astype(np.float32) + (1 - B1) * g32
    new_m2 = B2 * m2.astype(np.float32) + (1 - B2) * g32 * g32
    m1_hat = new_m1 / (1 - b1p)
    m2_hat = new_m2 / (1 - b2p)
    new_p = p32 * (1 - lr * WD) - lr * m1_hat / (np.sqrt(m2_hat) + EPS)
    return (new_p.astype(p.dtype), new_m1.astype(m1.dtype),
            new_m2.astype(m2.dtype))


@pytest.mark.parametrize("shape,dtype", [
    ((512, 1024), np.float32),       # lane-aligned, no padding
    ((3, 257), np.float32),          # unaligned -> padded tail
    ((24, 64, 64), "bfloat16"),      # slab-shaped bf16 (bench regime)
])
def test_fused_adamw_matches_composed(shape, dtype):
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if dtype == "bfloat16" else np.float32
    p = jnp.asarray(rng.randn(*shape), dt)
    g = jnp.asarray(rng.randn(*shape) * 0.1, dt)
    m1 = jnp.asarray(rng.randn(*shape) * 0.01, dt)
    m2 = jnp.asarray(np.abs(rng.randn(*shape)) * 0.001, dt)
    lr, b1p, b2p = 1e-3, B1 ** 3, B2 ** 3

    # p/m1/m2 are DONATED into the outputs (in-place contract): snapshot
    # the composed expectation before the call invalidates the inputs
    want_p, want_m1, want_m2 = _composed(
        np.asarray(p, np.float32), np.asarray(g, np.float32),
        np.asarray(m1, np.float32), np.asarray(m2, np.float32),
        lr, b1p, b2p)
    in_shape, in_dtype = p.shape, p.dtype
    got_p, got_m1, got_m2 = fused_adamw_update(
        p, g, m1, m2, lr, b1p, b2p,
        beta1=B1, beta2=B2, eps=EPS, wd=WD, interpret=True)

    tol = 1e-2 if dtype == "bfloat16" else 1e-6
    np.testing.assert_allclose(np.asarray(got_p, np.float32), want_p,
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(got_m1, np.float32), want_m1,
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(got_m2, np.float32), want_m2,
                               rtol=tol, atol=tol)
    assert got_p.shape == in_shape and got_p.dtype == in_dtype


def test_optimizer_routes_fused(monkeypatch):
    """AdamW(use_fused_kernel=True) without master weights must produce
    the same update as the composed path."""
    import paddle_tpu as pt

    rng = np.random.RandomState(1)
    w0 = rng.randn(16, 32).astype(np.float32)

    def one_step(use_fused):
        w = pt.to_tensor(w0.copy())
        w.stop_gradient = False
        opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=[w],
                                 multi_precision=False,
                                 use_fused_kernel=use_fused)
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        return np.asarray(w._value)

    a = one_step(False)
    b = one_step(True)
    np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-6)
