"""Benchmark: GPT train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline metric is tokens/sec/chip on the flagship GPT train step
(fwd + bwd + AdamW fused into a single XLA program via jit.to_static),
with MFU derived from the Megatron FLOPs formula. vs_baseline compares
MFU against the 45% north-star target (BASELINE.json: "GPT-3 1.3B
hybrid-parallel trains at >=45% MFU ... zero CUDA deps").

Memory discipline (round-2 postmortem: the TPU child died with
RESOURCE_EXHAUSTED and the bench fell off a CPU cliff):
  - flagship path = GPTStackedForPretraining: lax.scan over stacked
    blocks, remat per block, Pallas flash attention, bf16 matmuls with
    fp32 LayerNorm/softmax/residual (AMP O1 inside the fused block);
  - LM head goes through F.fused_linear_cross_entropy so [B,S,V] logits
    are never resident (chunked + remat);
  - jit.to_static donates the mutated captured state (params + AdamW
    moments) so the step updates alias in place — no double buffering;
  - the parent runs a BACK-OFF LADDER of TPU configs (1.3B bs=4 ->
    1.3B bs=2 -> gpt-small bs=16 -> gpt-small bs=2 seq=512) before ever
    falling back to CPU, and each child logs HBM usage via
    paddle_tpu.core.memory.

Resilience (round-1 postmortem, BENCH_r01 rc=1 / MULTICHIP_r01 rc=124):
the TPU backend (axon PJRT plugin) can fail OR hang — at init or later at
compile time — so no in-process defense suffices.  Structure:

  parent: probe backend init in a throwaway subprocess (cheap to kill),
          then run the measured workload in watchdog-timed children down
          the ladder; on total failure fall back to a clean-env CPU
          child; ALWAYS print exactly one JSON line.
  child (--child): the actual benchmark at the rung from BENCH_RUNG.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
_CPU_GUARD = "_PADDLE_TPU_BENCH_CPU_CHILD"

# bf16 matmuls for the MXU: the bench path uses AMP O1 (reference
# amp_guard list-based casting), so keep default matmul precision.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")
# persistent compilation cache: repeated bench runs skip recompiles
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")

# TPU back-off ladder: (model, batch, seq, steps, remat, regime).
# Rung 0 is the headline config — the BASELINE flagship GPT-3 1.3B model
# (largest batch that fits one v5e chip) in the pure-bf16 regime (bf16
# params AND bf16 AdamW moments, the reference's non-multi-precision
# adam) so the full optimizer state fits one chip.
# Round-4 note: jit.to_static's abstract scout (jax.eval_shape capture)
# means NO eager step of the model ever runs — peak residency is the
# compiled step's own (params 2.6G + moments 5.2G + remat'd activations
# for 1.3B pure-bf16), so larger batches fit than round 3's ladder.
# Later rungs trade shape for fitting so the bench ALWAYS produces an
# on-TPU number before considering the CPU cliff.
# regime: "bf16" = pure bf16 (bf16 params+moments, no masters), "master" =
# bf16 params + fp32 master weights/moments (halved param HBM traffic per
# step vs fp32, fp32-faithful update — needs ~2.4x the pure-bf16 optimizer
# HBM), "fp32" = fp32 params under AMP O1.  BENCH_PRECISION overrides the
# rung's regime for A/B runs.
_RUNGS = [
    ("1p3b", 8, 1024, 10, 1, "bf16"),
    ("1p3b", 4, 1024, 10, 1, "bf16"),
    ("1p3b", 2, 1024, 10, 1, "bf16"),
    ("small", 16, 1024, 20, 1, "bf16"),
    ("small", 2, 512, 20, 1, "fp32"),
]

_REGIMES = ("bf16", "master", "fp32")


def _parse_regime(tok: str, strict: bool = False) -> str:
    """BENCH_CONFIG back-compat: the old boolean pure_bf16 sixth field
    still parses ('1'/'true' -> bf16, '0'/'false' -> fp32).  ``strict``
    (the BENCH_PRECISION path) rejects unknown tokens instead — a typo'd
    regime must not silently record an fp32 measurement labeled as
    something else."""
    if tok in _REGIMES:
        return tok
    if strict:
        raise ValueError(
            f"BENCH_PRECISION={tok!r}: expected one of {_REGIMES}")
    return "bf16" if tok in ("1", "true", "True") else "fp32"


def _emit(metric, value, unit, vs_baseline):
    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }))
    sys.stdout.flush()


def _chip_spec(device_kind: str):
    """HardwareSpec (bf16 peak FLOP/s + HBM BW) by TPU generation — ONE
    table, owned by analysis/cost_model.py, so the MFU denominator and the
    roofline-fraction denominator can't drift apart.  device_kind strings
    vary ('TPU v5', 'TPU v5 lite', 'TPU v5p', ...); the PALLAS_AXON_TPU_GEN
    env override wins.  Child-only (imports paddle_tpu)."""
    from paddle_tpu.analysis import chip_spec

    return chip_spec(os.environ.get("PALLAS_AXON_TPU_GEN", "") or "",
                     device_kind or "")


def _peak_flops_per_chip(device_kind: str) -> float:
    return _chip_spec(device_kind).peak_flops


def _emit_roofline(phase, name, cost_reports_with_counts, spec, seconds,
                   on_tpu):
    """One ``*_roofline_fraction`` line: achieved FLOP/s over roofline-
    attainable FLOP/s for the phase's compiled program(s), from the static
    cost model (FLAGS_graph_cost) + the measured wall time.  Makes the MFU
    gap attributable per program: a low fraction on a memory-bound program
    means the gap is HBM streaming, not MXU idling."""
    try:
        flops = sum(c.flops * n for c, n in cost_reports_with_counts)
        nbytes = sum(c.bytes_upper * n for c, n in cost_reports_with_counts)
        if not flops or seconds <= 0:
            return
        intensity = flops / max(nbytes, 1)
        attainable = spec.attainable_flops(intensity)
        progs = ",".join(f"{c.program}x{n}"
                         for c, n in cost_reports_with_counts)
        # comm-aware denominators (Graph Lint v3): when any program has
        # modelled collectives, the UNHIDEABLE comm time (comm seconds x
        # (1 - overlap fraction)) is subtracted from the compute roofline's
        # wall clock instead of folding it into apparent MFU loss, and the
        # comm share is emitted as its own *_comm_roofline_fraction line.
        comm_s = sum(c.comm_seconds(spec) * n
                     for c, n in cost_reports_with_counts
                     if getattr(c, "collectives", None))
        compute_seconds = seconds
        comm_note = ""
        if comm_s > 0:
            ov = sum(c.overlap_fraction(spec) * c.comm_seconds(spec) * n
                     for c, n in cost_reports_with_counts
                     if getattr(c, "collectives", None)) / comm_s
            unhidden = comm_s * (1.0 - ov)
            compute_seconds = max(seconds - min(unhidden, seconds * 0.99),
                                  seconds * 0.01)
            comm_note = (" denominator=wall_minus_unhidden_comm "
                         f"comm_est_ms={comm_s * 1e3:.3f} "
                         f"overlap_frac={ov:.2f}")
        frac = (flops / compute_seconds) / attainable
        _emit(
            f"gpt_{name}_{phase}_roofline_fraction",
            round(frac, 4),
            f"frac=compute-roofline (programs={progs} gflop={flops / 1e9:.1f} "
            f"hbm_mib={nbytes / 2**20:.0f} intensity={intensity:.1f} "
            f"bound={'compute' if intensity >= spec.ridge else 'memory'} "
            f"attainable={attainable / 1e12:.1f}e12 chip={spec.name}"
            f"{comm_note} "
            f"on {'tpu' if on_tpu else 'cpu'})",
            0.0,
        )
        if comm_s > 0:
            # comm roofline: modelled ICI seconds / measured wall seconds —
            # how much of the step the static comm model accounts for
            _emit(
                f"gpt_{name}_{phase}_comm_roofline_fraction",
                round(comm_s / seconds, 4),
                f"frac=comm_est/wall (programs={progs} "
                f"comm_est_ms={comm_s * 1e3:.3f} wall_ms={seconds * 1e3:.3f} "
                f"ici_bw={spec.ici_bw / 1e9:.0f}GB/s chip={spec.name} "
                f"on {'tpu' if on_tpu else 'cpu'})",
                0.0,
            )
    except Exception as e:  # noqa: BLE001 — a cost line must never kill a metric
        sys.stderr.write(f"bench: roofline line ({phase}) failed: "
                         f"{type(e).__name__}: {str(e)[:300]}\n")


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------

def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] + [_REPO_ROOT]
    )
    env[_CPU_GUARD] = "1"
    return env


def _probe_hbm(timeout=None) -> float:
    """HBM capacity probe (GiB) in a throwaway subprocess: the axon PJRT
    plugin reports no memory_stats()/bytes_limit, so allocate 1-GiB device
    buffers until RESOURCE_EXHAUSTED and report how many fit.  Gives every
    OOM down-ladder a denominator ('model needs X of Y GiB').

    Timeout is env-overridable like the backend probe's
    (PADDLE_TPU_BENCH_PROBE_TIMEOUT / BENCH_PROBE_TIMEOUT, default 300s) —
    CI hosts that want a fast verdict shorten BOTH probes with one knob."""
    if timeout is None:
        timeout = float(
            os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT")
            or os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    code = r"""
import jax, jax.numpy as jnp
bufs = []
n = 0
try:
    for _ in range(256):
        # jnp.zeros materializes directly on the default device; no
        # device_put copy (double residency would undercount the boundary)
        bufs.append(jnp.zeros((1024, 1024, 256), jnp.float32))
        bufs[-1].block_until_ready()
        n += 1
except Exception:
    pass
# n == 0 means the FIRST allocation failed (backend/plugin error, not a
# capacity measurement) — report failure, not "0 GiB usable"
print("HBM_GIB", n if n > 0 else -1)
"""
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
        for line in (proc.stdout or "").splitlines():
            if line.startswith("HBM_GIB"):
                return float(line.split()[1])
    except subprocess.TimeoutExpired:
        pass
    return -1.0


def _probe_backend(timeout=240.0):
    """Backend-init probe in a throwaway subprocess.  Init can hang (not
    just raise), so this must be out-of-process and killable.

    Returns the platform string the probe reported ('tpu', 'cpu', ...) or
    None when the probe failed/hung.  'cpu' is a DEFINITIVE answer — the
    host has no TPU plugin — so the caller can skip retries and the TPU
    ladder instead of burning probe_timeout × retries (~24 min) first."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
        if proc.returncode == 0 and proc.stdout.strip():
            platform = proc.stdout.strip().splitlines()[-1].strip()
            sys.stderr.write(f"bench: backend ok: {platform}\n")
            return platform
        sys.stderr.write(f"bench: backend probe rc={proc.returncode}: "
                         f"{(proc.stderr or '').strip()[-500:]}\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench: backend probe timed out after {timeout}s\n")
    return None


# -- probe verdict cache (round-4/5 postmortem: r04/r05 burned 3x480s of
# probe timeouts per invocation, then SILENTLY fell back to CPU — the
# trajectory was blind for two rounds).  A definitive verdict is cached on
# disk for PADDLE_TPU_PROBE_CACHE_TTL seconds (default 30 min), so every
# bench/tool invocation in the same round pays the probe at most once. ----

def _probe_cache_path() -> str:
    return os.environ.get("PADDLE_TPU_PROBE_CACHE",
                          "/tmp/paddle_tpu_probe_verdict.json")


def _probe_cache_ttl() -> float:
    return float(os.environ.get("PADDLE_TPU_PROBE_CACHE_TTL", "1800"))


def _read_probe_cache():
    """Cached (platform, age_s) when fresh, else None."""
    try:
        with open(_probe_cache_path()) as f:
            d = json.load(f)
        age = time.time() - float(d["time"])
        if 0 <= age <= _probe_cache_ttl():
            return str(d["platform"]), age
    except Exception:  # noqa: BLE001 — a bad cache is just a cache miss
        pass
    return None


def _write_probe_cache(platform: str):
    try:
        path = _probe_cache_path()
        with open(path + ".tmp", "w") as f:
            json.dump({"platform": platform, "time": time.time()}, f)
        os.replace(path + ".tmp", path)
    except Exception:  # noqa: BLE001 — best-effort
        pass


def _probe_backend_adaptive():
    """Probe with ADAPTIVE timeout + short backoff instead of the old
    3 x 480s ladder: attempts start at PADDLE_TPU_BENCH_PROBE_TIMEOUT (or
    BENCH_PROBE_TIMEOUT, default 120s) and double per retry up to 480s,
    with 15s pauses — worst case ~14.5 min instead of ~25, and the common
    flaky-init case resolves in the first short attempt.  A definitive
    verdict (any platform string) is cached for the round.

    PADDLE_TPU_BENCH_PROBE_TOTAL (default 600s) caps the CUMULATIVE
    wall-clock the whole ladder may burn — attempts are clamped to the
    remaining budget and the ladder stops early once it is spent, so a
    wedged backend costs a bounded slice of the bench round no matter
    how the per-attempt knobs are tuned.

    Returns (platform_or_None, source) where source is 'cache' or
    'probe#N'."""
    cached = _read_probe_cache()
    if cached is not None:
        platform, age = cached
        sys.stderr.write(f"bench: probe verdict '{platform}' from cache "
                         f"(age {age:.0f}s)\n")
        return platform, "cache"
    base = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT")
                 or os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    retries = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "15"))
    total = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_TOTAL", "600"))
    t0 = time.monotonic()
    timeout = base
    tried = 0
    for attempt in range(1 + retries):
        remaining = total - (time.monotonic() - t0)
        if remaining <= 1.0:
            sys.stderr.write(
                f"bench: probe budget exhausted ({total:.0f}s total) "
                f"after {tried} attempts\n")
            break
        tried += 1
        platform = _probe_backend(timeout=min(timeout, remaining))
        if platform is not None:
            _write_probe_cache(platform)
            return platform, f"probe#{attempt + 1}"
        if attempt < retries:
            remaining = total - (time.monotonic() - t0)
            if remaining <= backoff + 1.0:
                sys.stderr.write(
                    f"bench: probe budget exhausted ({total:.0f}s total) "
                    f"after {tried} attempts\n")
                break
            sys.stderr.write(
                f"bench: probe attempt {attempt + 1} failed; retrying in "
                f"{backoff:.0f}s with timeout {min(timeout * 2, 480):.0f}s\n")
            time.sleep(backoff)
            timeout = min(timeout * 2, 480.0)
    return None, f"probe#{max(tried, 1)}"


def _run_child(env, timeout):
    """Run the measured workload in a watchdog-timed child; return its
    JSON metric lines (train + decode) or None.  A backend that
    initializes but hangs at compile/execute is killed by the timeout
    instead of wedging the whole bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, cwd=_REPO_ROOT, timeout=timeout,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench: child timed out after {timeout}s\n")
        return None
    sys.stderr.write((proc.stderr or "")[-3000:])
    if proc.returncode != 0:
        sys.stderr.write(f"bench: child rc={proc.returncode}\n")
        return None
    lines = [ln.strip() for ln in (proc.stdout or "").splitlines()
             if ln.strip().startswith("{") and '"metric"' in ln]
    if lines:
        return lines
    sys.stderr.write("bench: child produced no JSON line\n")
    return None


def parent():
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "900"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "900"))
    # the axon terminal can be transiently unavailable for many minutes
    # (session-claim recovery); the ADAPTIVE probe retries with doubling
    # timeouts + short backoff, and a definitive verdict is cached for the
    # round (see _probe_backend_adaptive)
    platform, probe_source = _probe_backend_adaptive()
    probed = platform is not None and platform != "cpu"
    if platform == "cpu":
        # definitive: no TPU plugin on this host — skip the TPU ladder
        sys.stderr.write("bench: probe reports CPU-only host; skipping "
                         "TPU ladder\n")
    lines = None
    failed_rungs = 0
    if probed:
        hbm = _probe_hbm()
        sys.stderr.write(f"bench: HBM capacity probe: "
                         f"{hbm:.0f} GiB usable\n" if hbm >= 0 else
                         "bench: HBM capacity probe failed\n")
        os.environ["BENCH_HBM_GIB"] = str(hbm)
        for rung in range(len(_RUNGS)):
            env = dict(os.environ)
            env["BENCH_RUNG"] = str(rung)
            lines = _run_child(env, tpu_timeout)
            if lines is not None:
                break
            failed_rungs += 1
            sys.stderr.write(f"bench: rung {rung} {_RUNGS[rung]} failed; "
                             "backing off\n")
    on_tpu_lines = lines is not None
    if lines is None:
        sys.stderr.write("bench: falling back to clean-env CPU child\n")
        lines = _run_child(_cpu_env(), cpu_timeout)
    # EXPLICIT backend line (ROADMAP item 1: a CPU fallback must be
    # visible in the BENCH_*.json trajectory, never silent): value 1.0 =
    # metrics below ran on TPU, 0.0 = the TPU rung was LOST this round —
    # the unit says why (probe timeout, CPU-only host, or rung failures)
    reason = ("ok" if on_tpu_lines
              else "cpu_only_host" if platform == "cpu"
              else "probe_failed" if platform is None
              else f"all_{failed_rungs}_tpu_rungs_failed")
    _emit("bench_backend", 1.0 if on_tpu_lines else 0.0,
          f"tpu_lost={0 if on_tpu_lines else 1} backend="
          f"{'tpu' if on_tpu_lines else 'cpu'} probe={platform or 'none'} "
          f"via={probe_source} reason={reason}",
          0.0)
    if lines is None:
        _emit("gpt_small_train_tokens_per_sec_per_chip", 0.0,
              "tokens/s (bench failed on both tpu and cpu paths)", 0.0)
        return
    for line in lines:
        print(line)
    sys.stdout.flush()


# ---------------------------------------------------------------------------
# child: the actual benchmark
# ---------------------------------------------------------------------------

def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu import analysis
    from paddle_tpu.core import memory as pt_memory
    from paddle_tpu.models import GPTStackedForPretraining, gpt_1p3b, gpt_small

    # static roofline cost reports for every compiled program (one extra
    # abstract trace per compile, zero compute): the *_roofline_fraction
    # lines below attribute the MFU gap per program
    pt.set_flags({"FLAGS_graph_cost": True})

    devs = jax.devices()
    on_tpu = devs[0].platform != "cpu"
    if on_tpu:
        custom = os.environ.get("BENCH_CONFIG")  # "model:bs:seq:steps:remat:regime"
        if custom:
            name, batch, seq, steps, remat, regime = custom.split(":")
            batch, seq, steps, remat = map(int, (batch, seq, steps, remat))
            regime = _parse_regime(regime)
        else:
            rung = int(os.environ.get("BENCH_RUNG", "0"))
            name, batch, seq, steps, remat, regime = _RUNGS[rung]
    else:
        # CPU fallback uses a toy shape so the bench always completes
        # (BENCH_CPU_STEPS lengthens the timed window for CPU A/B runs)
        name, batch, seq, steps, remat, regime = "small", 2, 128, 3, 1, "fp32"
        steps = int(os.environ.get("BENCH_CPU_STEPS", steps))
    env_precision = os.environ.get("BENCH_PRECISION")
    regime = (_parse_regime(env_precision, strict=True) if env_precision
              else _parse_regime(regime))
    param_dtype = "float32" if regime == "fp32" else "bfloat16"

    # remat config precedence: env pin > measured autotune-table winner >
    # rung default.  The table search space is (recompute_interval,
    # recompute_policy) on the stacked scan — tools/autotune.py times each
    # candidate train step once on-device and persists the winner under
    # the same shape-key discipline as the Pallas kernels.
    remat_policy = os.environ.get("BENCH_REMAT_POLICY") or None
    env_interval = os.environ.get("BENCH_REMAT_INTERVAL")
    if env_interval is not None:
        remat = int(env_interval)
    elif remat_policy is None:
        from paddle_tpu.analysis import autotune as _autotune

        mk_probe = gpt_1p3b if name == "1p3b" else gpt_small
        layers = mk_probe().num_layers if on_tpu else 2
        remat_shape = {"layers": layers,
                       "hidden": mk_probe().hidden_size if on_tpu else 768,
                       "batch": batch, "seq": seq}
        tuned = _autotune.kernel_params("train_remat", remat_shape,
                                        param_dtype)
        if tuned is not None:
            remat, remat_policy = _autotune.remat_params_to_config(tuned)
            sys.stderr.write(f"bench: train_remat table hit: "
                             f"interval={remat} policy={remat_policy}\n")

    if on_tpu:
        mk = gpt_1p3b if name == "1p3b" else gpt_small
        # remat policy: "dots" = selective remat (save MXU outputs,
        # recompute only VPU work in backward) — trades HBM for the ~33%
        # recompute FLOPs full remat pays; interval k groups k blocks per
        # checkpoint boundary on the stacked scan
        cfg = mk(hidden_dropout=0.0, attention_dropout=0.0,
                 max_position_embeddings=max(seq, 1024),
                 recompute_interval=remat,
                 recompute_policy=remat_policy,
                 use_flash_attention=True)
    else:
        cfg = gpt_small(hidden_dropout=0.0, attention_dropout=0.0,
                        recompute_interval=remat,
                        recompute_policy=remat_policy)
        cfg.num_layers = 2

    pt.seed(0)
    model = GPTStackedForPretraining(cfg)
    if regime in ("bf16", "master"):
        # bf16 params (halved parameter HBM traffic per step): "bf16" is
        # the pure regime (bf16 moments, no masters — the reference's
        # non-multi-precision adam); "master" keeps fp32 master weights +
        # fp32 moments in the optimizer (reference multi_precision adam) —
        # the update reads/writes the masters, convergence tracks fp32
        pt.amp.decorate(model, level="O2", dtype="bfloat16")
    # BENCH_FUSED_ADAM=1: route the update through the owned Pallas
    # multi-tensor kernel (ops/pallas_kernels/fused_adamw.py) for A/B
    # against the XLA-composed chain
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                             multi_precision=regime != "bf16",
                             use_fused_kernel=os.environ.get(
                                 "BENCH_FUSED_ADAM") in ("1", "true", "True"))

    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")

    # ONE donated fused program: fwd + bwd + AdamW update (params, moments
    # and masters alias in place; Graph Lint GL004 gates regressions here)
    train_step = pt.optimizer.FusedTrainStep(
        lambda ids, labels: model(ids, labels=labels), opt,
        amp_level="O1", amp_dtype="bfloat16")

    # async host->device input pipeline: a small pool of distinct host
    # batches cycles through a depth-2 device prefetcher, so the timed
    # loop's device_put overlaps the running step; consumer wait (the
    # input stall the pipeline hides) is measured per batch
    _pool = [(rng.randint(0, cfg.vocab_size, (batch, seq)),
              rng.randint(0, cfg.vocab_size, (batch, seq)))
             for _ in range(min(4, steps))]

    def _host_batches(n):
        for i in range(n):
            yield _pool[i % len(_pool)]

    # Phase-logged protocol (round-3 postmortem: the failing child died at
    # the final sync with no indication of WHICH phase exhausted HBM).
    # With the abstract scout, call 1 = zero-compute capture + compile +
    # first compiled step; later calls are steady-state.
    pt_memory.log_memory("after model+optimizer build")
    try:
        loss = train_step(ids, labels)
        float(loss)  # sync phase 1
    except Exception:
        pt_memory.log_memory("FAILED during compile+first step")
        raise
    pt_memory.log_memory("after compile+first step")
    try:
        for _ in range(2):
            loss = train_step(ids, labels)
        float(loss)
    except Exception:
        pt_memory.log_memory("FAILED during steady-state warmup")
        raise
    pt_memory.log_memory("after steady-state warmup")

    from paddle_tpu.core import op_cache as pt_op_cache
    from paddle_tpu.io import DevicePrefetcher

    disp0 = train_step.dispatch_count
    eager0 = pt_op_cache.summary()["calls"]
    # BENCH_INPUT_MODE=sync: per-step inline host->device conversion (the
    # no-pipeline baseline) for A/B against the default prefetch path
    input_mode = os.environ.get("BENCH_INPUT_MODE", "prefetch")
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:
        import jax.profiler as _jprof
        _jprof.start_trace(profile_dir)
    prefetcher = None
    try:
        # the prefetcher is constructed INSIDE the timed window: its
        # producer thread starts issuing device_puts immediately, and
        # letting that head start run before t0 would flatter the
        # prefetch arm vs the sync baseline by ~depth/steps of transfer
        t0 = time.perf_counter()
        if input_mode != "sync":
            prefetcher = DevicePrefetcher(_host_batches(steps), depth=2)
            for bids, blabels in prefetcher:
                loss = train_step(bids, blabels)
        else:
            for hids, hlabels in _host_batches(steps):
                loss = train_step(pt.to_tensor(hids, dtype="int64"),
                                  pt.to_tensor(hlabels, dtype="int64"))
        final = float(loss)  # forces completion of the async chain
        dt = time.perf_counter() - t0
    finally:
        if prefetcher is not None:
            prefetcher.close()
        if profile_dir:
            _jprof.stop_trace()
            sys.stderr.write(f"bench: profile trace in {profile_dir}\n")
    assert np.isfinite(final), f"bench diverged: loss={final}"
    pf_stats = (prefetcher.stats() if prefetcher is not None
                else {"stall_seconds_total": float("nan")})
    stall_share = (pf_stats["stall_seconds_total"] / dt
                   if dt > 0 and prefetcher is not None else float("nan"))
    # per-step dispatch count: ONE fused program per step + any eager
    # dispatches that leaked into the timed loop (should be zero)
    disp_fused = train_step.dispatch_count - disp0
    disp_eager = pt_op_cache.summary()["calls"] - eager0
    disp_per_step = (disp_fused + disp_eager) / max(steps, 1)

    peak_mib = pt_memory.max_memory_allocated() / 2**20
    sys.stderr.write(pt_memory.memory_summary() + "\n")

    # eager dispatch-cache counters: the measured loop is jit.to_static
    # (cache falls back under tracing by design), but model/optimizer
    # build + data prep run eager — the hit rate here tracks how much of
    # the off-to_static surface rides the compiled fast path
    cache_sum = pt_op_cache.summary()
    sys.stderr.write("bench: dispatch-cache: " + json.dumps(cache_sum) + "\n")

    tokens_per_sec = batch * seq * steps / dt

    # Megatron-LM FLOPs/iteration: 72 b s L h^2 (1 + s/(6h) + V/(12 L h))
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    flops_per_iter = 72 * batch * seq * L * h * h * (1 + seq / (6 * h) + V / (12 * L * h))
    model_flops_per_sec = flops_per_iter * steps / dt
    kind = getattr(devs[0], "device_kind", "")
    spec = _chip_spec(kind)
    peak = spec.peak_flops
    mfu = model_flops_per_sec / peak
    hbm = os.environ.get("BENCH_HBM_GIB", "?")

    # MFU denominator recorded so the number is auditable (round-3 weak #4)
    _emit(
        f"gpt_{name}_train_tokens_per_sec_per_chip",
        round(tokens_per_sec, 1),
        f"tokens/s (bs={batch} seq={seq} mfu={mfu:.3f} "
        f"regime={regime} remat={cfg.recompute_interval}:"
        f"{cfg.recompute_policy or 'full'} "
        f"stall_share={stall_share:.4f} "
        f"disp_per_step={disp_per_step:.2f} "
        f"peak_hbm={peak_mib:.0f}MiB hbm_cap={hbm}GiB "
        f"device='{kind}' peak_flops={peak/1e12:.0f}e12 "
        f"opcache_calls={cache_sum['calls']} "
        f"opcache_hit={cache_sum['hit_rate']:.3f} "
        f"on {'tpu' if on_tpu else 'cpu'})",
        round(mfu / 0.45, 4),
    )
    train_costs = train_step.cost_reports()
    # exact-FLOPs MFU: the static cost model counts the compiled program's
    # actual FLOPs (2NK dots from dimension_numbers — remat recompute
    # included), so this line moves when a REAL lever moves (remat policy,
    # fused head, regime) where the heuristic token formula cannot.
    # Companion line gpt_*_train_mfu sits next to the roofline fraction.
    if train_costs:
        exact_flops = train_costs[0].flops
        exact_mfu = (exact_flops * steps / dt) / peak
        _emit(
            f"gpt_{name}_train_mfu",
            round(exact_mfu, 4),
            f"frac (cost-model program flops={exact_flops / 1e9:.1f}gflop "
            f"x{steps} steps / {dt:.3f}s / peak={peak / 1e12:.0f}e12; "
            f"heuristic_mfu={mfu:.4f} stall_share={stall_share:.4f} "
            f"disp_per_step={disp_per_step:.2f} regime={regime} "
            f"on {'tpu' if on_tpu else 'cpu'})",
            round(exact_mfu / 0.45, 4),
        )
        _emit_roofline("train", name, [(train_costs[0], steps)], spec, dt,
                       on_tpu)

    # ---- decode (serving) metric: prefill + autoregressive decode over the
    # donated KV cache, same ladder model.  Two compiled programs total
    # (prefill + one decode step); the loop is retrace-free and the cache
    # donation keeps HBM flat across steps (delta recorded in the unit).
    if on_tpu:
        dec_bs, prompt_len, new_tokens = 8, 128, 64
        # smallest 128-multiple that fits the request: the cache is live
        # ON TOP of the still-resident train state, and on the ladder's
        # tight rungs a seq-sized cache (1024+) would be 4-5x more HBM
        # than the 256 positions actually decoded
        max_seq_cache = -(-(prompt_len + new_tokens) // 128) * 128
    else:
        dec_bs, prompt_len, new_tokens = 2, 16, 8
        max_seq_cache = 64
    prompt = pt.to_tensor(
        rng.randint(0, cfg.vocab_size, (dec_bs, prompt_len)), dtype="int64")
    try:
        # warmup compiles prefill + decode; the timed call reuses both.
        # cost registry cleared first so this phase's reports are
        # unambiguously the decode engine's (names repeat across phases)
        analysis.clear_cost_reports()
        model.generate(prompt, max_new_tokens=2, max_seq_len=max_seq_cache,
                       cache_dtype="bfloat16")
        mem_before = pt_memory.memory_allocated()
        t0 = time.perf_counter()
        out_ids = model.generate(prompt, max_new_tokens=new_tokens,
                                 max_seq_len=max_seq_cache,
                                 cache_dtype="bfloat16")
        np.asarray(out_ids.numpy())  # force completion of the async chain
        dec_dt = time.perf_counter() - t0
        mem_after = pt_memory.memory_allocated()
        pt_memory.log_memory("after decode bench")
        decode_tps = dec_bs * new_tokens / dec_dt
        from paddle_tpu.models import generation as _gen

        tc = _gen.trace_counts()
        _emit(
            f"gpt_{name}_decode_tokens_per_sec_per_chip",
            round(decode_tps, 1),
            f"tokens/s (bs={dec_bs} prompt={prompt_len} new={new_tokens} "
            f"cache=[{cfg.num_layers},{dec_bs},{cfg.num_heads},"
            f"{max_seq_cache},{cfg.head_dim}]xbf16 "
            f"mem_delta={(mem_after - mem_before) / 2**20:.1f}MiB "
            f"traces={tc} on {'tpu' if on_tpu else 'cpu'})",
            0.0,
        )
        dec_costs = {c.program: c for c in analysis.cost_reports()}
        pairs = [(c, n) for c, n in (
            (dec_costs.get("prefill_step"), 1),
            (dec_costs.get("decode_step"), max(new_tokens - 1, 1)),
        ) if c is not None]
        _emit_roofline("decode", name, pairs, spec, dec_dt, on_tpu)
    except Exception as e:  # noqa: BLE001 — decode must not kill the train metric
        sys.stderr.write(f"bench: decode bench failed: {type(e).__name__}: "
                         f"{str(e)[:500]}\n")
        _emit(f"gpt_{name}_decode_tokens_per_sec_per_chip", 0.0,
              "tokens/s (decode bench failed; see stderr)", 0.0)

    # ---- serving (continuous batching) metric: paged KV cache + ONE
    # fused mixed prefill/decode step over all slots (ragged work-list
    # kernel), offered load > slot count so admission/retirement churn is
    # part of the measurement.  One compiled program (all-greedy traffic);
    # trace counters + ragged grid occupancy recorded in the unit prove
    # the step never retraced and show how full the launch ran.
    try:
        from paddle_tpu.serving import (
            ServingEngine, reset_serve_trace_counts, serve_trace_counts,
        )

        # prefix_cache on: random prompts share no prefixes so the hit
        # rate prints ~0 here (serving_bench --prefix-dist is the shared-
        # prefix traffic bench) — the bench line pins the cache-enabled
        # hot path's throughput trajectory
        if on_tpu:
            s_kw = dict(num_slots=8, page_size=128, max_context=512,
                        cache_dtype="bfloat16", prefix_cache=True)
            s_new, n_req, plens = 32, 16, (64, 200, 120, 380)
        else:
            s_kw = dict(num_slots=2, page_size=16, max_context=64,
                        cache_dtype="bfloat16", prefix_cache=True)
            s_new, n_req, plens = 4, 4, (8, 20, 12, 16)
        reset_serve_trace_counts()
        analysis.clear_cost_reports()  # this phase's programs only
        # mesh-sharded serving (docs/serving.md "Sharded serving"):
        # BENCH_SERVING_MESH=dp,mp runs the phase on a ShardedServingEngine
        # — dp replicas x mp tensor-parallel chips behind one placement
        # scheduler.  Default 1,1 keeps the single-chip trajectory
        # comparable; insufficient devices fall back with a stderr note.
        s_dp, s_mp = 1, 1
        raw_mesh = os.environ.get("BENCH_SERVING_MESH", "1,1")
        try:
            s_dp, s_mp = (int(x) for x in raw_mesh.split(","))
        except ValueError:
            sys.stderr.write(f"bench: BENCH_SERVING_MESH={raw_mesh!r} "
                             "unparsable (want dp,mp); using 1,1\n")
        if s_dp < 1 or s_mp < 1:
            sys.stderr.write(f"bench: BENCH_SERVING_MESH={raw_mesh!r}: "
                             "axes must be >= 1; using 1,1\n")
            s_dp = s_mp = 1
        if s_dp * s_mp > len(jax.devices()):
            sys.stderr.write(
                f"bench: BENCH_SERVING_MESH={s_dp},{s_mp} needs "
                f"{s_dp * s_mp} devices, host has {len(jax.devices())}; "
                "using 1,1\n")
            s_dp = s_mp = 1
        # speculative serving (docs/serving.md "Speculative decoding"):
        # BENCH_SPECULATE=draft,k opts the phase into a SpeculativeEngine
        # — 'same' (acceptance 1.0) or '<n>layer' truncated draft, k
        # proposals per slot per tick.  Off by default so the trajectory
        # stays comparable; mutually exclusive with a >1 serving mesh.
        s_spec = None
        raw_spec = os.environ.get("BENCH_SPECULATE", "")
        if raw_spec:
            try:
                sd, sk = raw_spec.split(",")
                if sd != "same" and not (sd.endswith("layer")
                                         and sd[:-len("layer")].isdigit()):
                    raise ValueError(sd)
                s_spec = (sd, int(sk))
            except ValueError:
                sys.stderr.write(f"bench: BENCH_SPECULATE={raw_spec!r} "
                                 "unparsable (want same|<n>layer,k); "
                                 "ignoring\n")
            if s_spec and s_dp * s_mp > 1:
                sys.stderr.write("bench: BENCH_SPECULATE ignored under "
                                 "BENCH_SERVING_MESH>1,1 (speculation is "
                                 "per-replica; use engine_factory)\n")
                s_spec = None
        # quantized serving (docs/serving.md "Quantized serving"):
        # BENCH_KV_DTYPE=float32|bfloat16|int8 flips the paged pool
        # regime, BENCH_WEIGHT_DTYPE=int8 PTQs the decode projections.
        # Off by default so the trajectory stays comparable; the weight
        # PTQ runs on a CLONE because quantize_for_serving mutates.
        s_model = model
        s_kvd = os.environ.get("BENCH_KV_DTYPE", "")
        if s_kvd:
            if s_kvd in ("float32", "bfloat16", "int8"):
                s_kw["kv_dtype"] = s_kvd
            else:
                sys.stderr.write(f"bench: BENCH_KV_DTYPE={s_kvd!r} unknown "
                                 "(want float32|bfloat16|int8); ignoring\n")
        s_wd = os.environ.get("BENCH_WEIGHT_DTYPE", "")
        if s_wd:
            if s_wd == "int8":
                from paddle_tpu.distributed.serving_mesh import clone_model

                s_model = clone_model(model)
                s_kw["weight_dtype"] = "int8"
            else:
                sys.stderr.write(f"bench: BENCH_WEIGHT_DTYPE={s_wd!r} "
                                 "unknown (want int8); ignoring\n")
        if s_dp * s_mp > 1:
            from paddle_tpu.serving import ShardedServingEngine

            eng = ShardedServingEngine(s_model, dp=s_dp, mp=s_mp, **s_kw)
        elif s_spec is not None:
            from paddle_tpu.serving import SpeculativeEngine

            if s_spec[0] == "same":
                s_draft = s_model
            else:
                from paddle_tpu.models import truncated_draft

                s_draft = truncated_draft(s_model,
                                          int(s_spec[0][:-len("layer")]))
            eng = SpeculativeEngine(s_model, s_draft, spec_k=s_spec[1],
                                    **s_kw)
        else:
            eng = ServingEngine(s_model, **s_kw)
        # warmup compiles the fused greedy step — one request per dp
        # replica (least-loaded placement seats each on its own replica)
        # so NO replica's SPMD compile lands in the timed window
        for _ in range(s_dp):
            eng.submit(rng.randint(0, cfg.vocab_size, (plens[0],)), 2)
        eng.run_until_idle()
        m0 = eng.metrics()
        mem_before = pt_memory.memory_allocated()
        t0 = time.perf_counter()
        s_reqs = [eng.submit(
            rng.randint(0, cfg.vocab_size, (plens[i % len(plens)],)), s_new)
            for i in range(n_req)]
        eng.run_until_idle()
        s_dt = time.perf_counter() - t0
        mem_after = pt_memory.memory_allocated()
        s_tokens = sum(len(r.tokens) for r in s_reqs)
        mets = eng.metrics()
        tc = serve_trace_counts()
        # occupancy over the measured window only: the engine totals are
        # cumulative and include the warmup request's mostly-empty steps
        # (same subtraction as tools/serving_bench.py)
        d_wcap = mets["work_capacity"] - m0["work_capacity"]
        d_rcap = mets["block_row_capacity"] - m0["block_row_capacity"]
        grid_occ = ((mets["work_items"] - m0["work_items"]) / d_wcap
                    if d_wcap else 0.0)
        q_row_occ = ((mets["block_rows"] - m0["block_rows"]) / d_rcap
                     if d_rcap else 0.0)
        pt_memory.log_memory("after serving bench")
        # per-chip pool accounting: the head-sharded pool holds 1/mp of
        # the page bytes per chip; aggregate page capacity grows with dp
        pool_per_chip_mib = mets["cache_bytes_per_chip"] / 2 ** 20
        _emit(
            f"gpt_{name}_serving_tokens_per_sec_per_chip",
            round(s_tokens / s_dt / max(s_dp * s_mp, 1), 1),
            f"tokens/s (mesh={s_dp}x{s_mp} slots={s_kw['num_slots']} "
            f"reqs={n_req} "
            f"page={s_kw['page_size']} ctx={s_kw['max_context']} "
            f"new={s_new} pool={mets['pages_capacity']}pages "
            f"kv_dtype={s_kw.get('kv_dtype') or s_kw['cache_dtype']} "
            f"weight_dtype={s_kw.get('weight_dtype') or 'native'} "
            f"pool_per_chip={pool_per_chip_mib:.2f}MiB "
            f"aggregate_tps={s_tokens / s_dt:.1f} "
            f"completed={mets['completed']} "
            f"grid_occ={grid_occ:.3f} "
            f"q_row_occ={q_row_occ:.3f} "
            f"prefix_hit_rate={mets.get('prefix_hit_rate', 0.0):.3f} "
            f"mem_delta={(mem_after - mem_before) / 2**20:.1f}MiB "
            + (f"spec={s_spec[0]},k={s_spec[1]} "
               f"accept_rate={mets.get('spec_acceptance_rate', 0.0):.3f} "
               if s_spec is not None else "")
            + f"traces={tc} on {'tpu' if on_tpu else 'cpu'})",
            0.0,
        )
        # per-request SLO percentiles from the engine's telemetry
        # histograms (TTFT = submission -> first token, queue included;
        # ITL = gap between consecutive tokens of one request) — the
        # latency companions to the throughput line above
        # sharded runs: per-request SLO histograms are per replica and do
        # not merge exactly — quote replica 0 as the representative
        slo = mets.get("slo") or (
            mets["per_replica"][0].get("slo", {})
            if mets.get("per_replica") else {})

        def _ms(h, q):
            return round(h.get(q, 0.0) * 1000.0, 3)

        tt, it = slo.get("ttft", {}), slo.get("itl", {})
        sharded_run = s_dp * s_mp > 1
        print(json.dumps({
            "metric": f"gpt_{name}_serving_slo_ms",
            "mesh": f"{s_dp}x{s_mp}",
            # sharded runs quote ONE replica's histograms (percentiles of
            # different replicas do not merge); the scope tag keeps the
            # trajectory discontinuity visible when comparing commits
            "scope": "replica0" if sharded_run else "engine",
            "ttft_p50": _ms(tt, "p50"), "ttft_p95": _ms(tt, "p95"),
            "ttft_p99": _ms(tt, "p99"), "ttft_count": int(tt.get("count", 0)),
            "itl_p50": _ms(it, "p50"), "itl_p95": _ms(it, "p95"),
            "itl_p99": _ms(it, "p99"),
            "queue_wait_p50": _ms(slo.get("queue_wait", {}), "p50"),
            "unit": "ms (per-request serving SLOs; includes the warmup "
                    "request's compile-dominated TTFT sample"
                    + ("; replica-0 scope on a sharded mesh)" if sharded_run
                       else ")"),
        }))
        sys.stdout.flush()
        srv_costs = {c.program: c for c in analysis.cost_reports()}
        # exact invocation counts from the engine's own counter:
        # fused_steps counts actual fused dispatches (idle/recovery ticks
        # don't run the program)
        pairs = [(c, n) for c, n in (
            (srv_costs.get("fused_step"),
             max(int(mets["fused_steps"] - m0["fused_steps"]), 1)),
        ) if c is not None]
        _emit_roofline("serving", name, pairs, spec, s_dt, on_tpu)
        eng.close()
    except Exception as e:  # noqa: BLE001 — serving must not kill prior metrics
        sys.stderr.write(f"bench: serving bench failed: {type(e).__name__}: "
                         f"{str(e)[:500]}\n")
        _emit(f"gpt_{name}_serving_tokens_per_sec_per_chip", 0.0,
              "tokens/s (serving bench failed; see stderr)", 0.0)


if __name__ == "__main__":
    if "--child" in sys.argv:
        main()
    else:
        parent()
