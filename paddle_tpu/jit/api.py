"""Trace-and-compile: the dy2static analog, TPU-first.

Reference: python/paddle/jit/api.py:233 ``to_static`` +
dy2static/program_translator.py (StaticFunction/ConcreteProgram/
PartialProgramLayer executing a captured ProgramDesc via run_program op).

TPU-native redesign: instead of AST-rewriting python into a ProgramDesc and
interpreting it, we *functionalize* the imperative program into a single
jitted XLA computation:

1. A first "scout" call runs eagerly while logging (a) every leaf Tensor the
   function reads (captured state: parameters, buffers, RNG keys, optimizer
   moments) and (b) every Tensor whose value is re-bound (mutations:
   optimizer updates, RNG advance, buffer writes).
2. Subsequent calls execute a cached ``jax.jit`` program whose inputs are
   (example args + captured state) and whose outputs are (results + mutated
   state), written back after each call.

The whole train step — forward, ``loss.backward()``'s VJP chain, and the
optimizer update — traces into ONE fused program: XLA sees the entire graph,
so there is no per-op dispatch, no interpreter, and remat/fusion apply
globally. This is why eager-mode overhead does not bound performance
(SURVEY.md §7 "hard parts" (a)).
"""
from __future__ import annotations

import functools
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..tensor import Tensor
from ..ops import dispatch


class _JitState(threading.local):
    def __init__(self):
        self.tracing = False


_jit_state = _JitState()


def in_tracing() -> bool:
    return _jit_state.tracing


def _tree_flatten(obj, tensors: List[Tensor]):
    """Flatten nested python containers, extracting Tensors; returns a spec."""
    if isinstance(obj, Tensor):
        tensors.append(obj)
        return ("t", len(tensors) - 1)
    if isinstance(obj, (list, tuple)):
        specs = [_tree_flatten(o, tensors) for o in obj]
        return ("seq", type(obj).__name__, specs)
    if isinstance(obj, dict):
        keys = list(obj.keys())
        specs = [_tree_flatten(obj[k], tensors) for k in keys]
        return ("dict", keys, specs)
    return ("leaf", obj)


def _tree_unflatten(spec, raws):
    kind = spec[0]
    if kind == "t":
        return Tensor(raws[spec[1]])
    if kind == "seq":
        seq = [_tree_unflatten(s, raws) for s in spec[2]]
        return tuple(seq) if spec[1] == "tuple" else seq
    if kind == "dict":
        return {k: _tree_unflatten(s, raws) for k, s in zip(spec[1], spec[2])}
    return spec[1]


def _sig_of(tensors: List[Tensor], static_repr: str):
    return (
        tuple((tuple(t._value.shape), str(t._value.dtype)) for t in tensors),
        static_repr,
    )


class _CompiledEntry:
    __slots__ = (
        "jitted",
        "captured",
        "mut_caps",
        "ro_caps",
        "mutated_order",
        "out_spec",
        "n_args",
        "gen_threshold",
        "_scout_result",
    )

    def __init__(self):
        self.jitted = None
        self.captured: List[Tensor] = []
        # captured state split by the scout pass: tensors the function
        # re-binds (params, moments, RNG state) vs read-only state.  The
        # mutated ones are DONATED to XLA (jax.jit donate_argnums) so the
        # update aliases into the same HBM buffers instead of
        # double-buffering params+moments across the step — the analog of
        # the reference's inplace op outputs (paddle inplace pass).
        self.mut_caps: List[Tensor] = []
        self.ro_caps: List[Tensor] = []
        self.mutated_order: List[Tensor] = []
        self.out_spec = None
        self.n_args = 0
        self.gen_threshold = 0
        self._scout_result = None


class StaticFunction:
    """Callable wrapping a compiled imperative function
    (reference program_translator.py:305)."""

    def __init__(self, fn, input_spec=None, build_strategy=None, backend=None):
        self._fn = fn
        self._cache: Dict[Any, _CompiledEntry] = {}
        functools.update_wrapper(self, fn)

    @property
    def code_cache(self):
        return self._cache

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction.__new__(StaticFunction)
        bound._fn = self._fn.__get__(instance, owner)
        bound._cache = self._cache  # share compiled programs per class fn
        return bound

    def __call__(self, *args, **kwargs):
        arg_tensors: List[Tensor] = []
        arg_spec = _tree_flatten((args, kwargs), arg_tensors)
        key = _sig_of(arg_tensors, repr(arg_spec))
        bound_self = getattr(self._fn, "__self__", None)
        if bound_self is not None:
            key = (key, id(bound_self))

        entry = self._cache.get(key)
        if entry is None:
            # warmup call: run eagerly so lazily-created state (optimizer
            # moments, BN stats, caches) comes into existence before capture
            entry = _CompiledEntry()
            self._cache[key] = entry
            return self._fn(*args, **kwargs)
        if entry.jitted is None:
            entry = self._scout_and_compile(key, args, kwargs, arg_tensors)
            # scout call already produced results eagerly
            return entry._scout_result

        raw_args = [t._value for t in arg_tensors]
        raw_mut = [t._value for t in entry.mut_caps]
        raw_ro = [t._value for t in entry.ro_caps]
        out_raws, new_states = entry.jitted(raw_args, raw_mut, raw_ro)
        for t, v in zip(entry.mutated_order, new_states):
            t._value = v  # direct write; no re-logging
        return _tree_unflatten(entry.out_spec, list(out_raws))

    # -- compilation -------------------------------------------------------
    def _scout_and_compile(self, key, args, kwargs, arg_tensors):
        entry = self._cache.get(key) or _CompiledEntry()

        # 1. scout: run eagerly, log reads of leaf tensors + mutations
        from .. import tensor as _tensor_mod

        _tensor_mod._GENERATION[0] += 1
        threshold = _tensor_mod._GENERATION[0]
        entry.gen_threshold = threshold

        read_log: Dict[int, Tensor] = {}
        mut_log: Dict[int, Tensor] = {}
        prev_read = dispatch._trace_state.read_log
        prev_epoch = dispatch._trace_state.read_epoch
        prev_mut = dispatch._trace_state.mutation_log
        dispatch._trace_state.read_log = read_log
        dispatch._trace_state.read_epoch = threshold
        dispatch._trace_state.mutation_log = mut_log
        try:
            result = self._fn(*args, **kwargs)
        finally:
            dispatch._trace_state.read_log = prev_read
            dispatch._trace_state.read_epoch = prev_epoch
            dispatch._trace_state.mutation_log = prev_mut

        arg_ids = {id(t) for t in arg_tensors}
        captured = [t for tid, t in read_log.items() if tid not in arg_ids]
        # pre-existing mutated tensors must be carried even if never read
        for tid, t in mut_log.items():
            if tid not in arg_ids and t._gen < threshold and not any(
                t is c for c in captured
            ):
                captured.append(t)
        entry.captured = captured
        # split: state the scout saw re-bound is donated; read-only is not
        mut_ids = set(mut_log.keys())
        entry.mut_caps = [t for t in captured if id(t) in mut_ids]
        entry.ro_caps = [t for t in captured if id(t) not in mut_ids]
        entry.n_args = len(arg_tensors)

        out_tensors: List[Tensor] = []
        entry.out_spec = _tree_flatten(result, out_tensors)
        entry._scout_result = result  # type: ignore[attr-defined]

        # 2. build the pure function over (args, mut-captured, ro-captured)
        fn = self._fn
        mut_list = entry.mut_caps
        ro_list = entry.ro_caps
        arg_spec = _tree_flatten((args, kwargs), [])

        def pure_fn(raw_args, raw_mut, raw_ro):
            # bind tracers into the live Tensor objects, run, then restore
            cap_pairs = list(zip(mut_list, raw_mut)) + list(zip(ro_list, raw_ro))
            snapshot = [(t, t._value, t.grad) for t, _ in cap_pairs]
            mut: Dict[int, Tensor] = {}
            prev_m = dispatch._trace_state.mutation_log
            prev_t = _jit_state.tracing
            dispatch._trace_state.mutation_log = mut
            _jit_state.tracing = True
            try:
                for t, rv in cap_pairs:
                    t._value = rv
                a, kw = _tree_unflatten(arg_spec, list(raw_args))
                res = fn(*a, **kw)
                outs: List[Tensor] = []
                _tree_flatten(res, outs)
                out_raws = tuple(o._value for o in outs)
                # stable mutation order: ALL donated tensors first (their
                # final values alias the donated input buffers — tensors the
                # trace didn't touch pass through unchanged), then any other
                # pre-existing mutated tensors discovered during the trace;
                # call-local tensors die with the call
                order = list(mut_list)
                extra = [
                    t
                    for t in mut.values()
                    if t._gen < entry.gen_threshold
                    and not any(t is o for o in order)
                    and not any(t is r for r in ro_list)
                ]
                order.extend(extra)
                ro_mutated = [t for t in ro_list if id(t) in mut]
                order.extend(ro_mutated)
                entry.mutated_order = order
                new_states = tuple(t._value for t in order)
                return out_raws, new_states
            finally:
                dispatch._trace_state.mutation_log = prev_m
                _jit_state.tracing = prev_t
                for t, v, g in snapshot:
                    t._value = v
                    t.grad = g

        entry.jitted = jax.jit(pure_fn, donate_argnums=(1,))
        self._cache[key] = entry
        return entry


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper compiling an imperative function
    (reference jit/api.py:233)."""

    def decorate(fn):
        if isinstance(fn, StaticFunction):
            return fn
        # wrapping a Layer: compile its forward
        from ..nn.layer import Layer

        if isinstance(fn, Layer):
            layer = fn
            layer.forward = StaticFunction(layer.forward)
            return layer
        return StaticFunction(fn, input_spec, build_strategy, backend)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._paddle_tpu_not_to_static = True
    return fn
