"""Elementwise and scalar math ops.

Reference surface: python/paddle/tensor/math.py + ops.yaml elementwise
entries. All lower to jax.numpy → StableHLO; XLA fuses chains of these into
single VPU loops, so there is no need for the reference's handwritten
broadcast/elementwise CUDA templates (paddle/phi/kernels/funcs/broadcast_function.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from . import dispatch
from ._factory import binary_op, ensure_tensor, unary_op

# ---- binary arithmetic -------------------------------------------------------
add = binary_op(jnp.add, "add")
subtract = binary_op(jnp.subtract, "subtract")
multiply = binary_op(jnp.multiply, "multiply")
divide = binary_op(jnp.divide, "divide")
mod = binary_op(jnp.mod, "mod")
remainder = mod
floor_mod = mod
floor_divide = binary_op(jnp.floor_divide, "floor_divide")
pow = binary_op(jnp.power, "pow")  # noqa: A001
maximum = binary_op(jnp.maximum, "maximum")
minimum = binary_op(jnp.minimum, "minimum")
fmax = binary_op(jnp.fmax, "fmax")
fmin = binary_op(jnp.fmin, "fmin")
atan2 = binary_op(jnp.arctan2, "atan2")
hypot = binary_op(jnp.hypot, "hypot")
logaddexp = binary_op(jnp.logaddexp, "logaddexp")
heaviside = binary_op(jnp.heaviside, "heaviside")
gcd = binary_op(jnp.gcd, "gcd")
lcm = binary_op(jnp.lcm, "lcm")
nextafter = binary_op(jnp.nextafter, "nextafter")
copysign = binary_op(jnp.copysign, "copysign")

# ---- unary -------------------------------------------------------------------
exp = unary_op(jnp.exp, "exp")
expm1 = unary_op(jnp.expm1, "expm1")
log = unary_op(jnp.log, "log")
log2 = unary_op(jnp.log2, "log2")
log10 = unary_op(jnp.log10, "log10")
log1p = unary_op(jnp.log1p, "log1p")
sqrt = unary_op(jnp.sqrt, "sqrt")
rsqrt = unary_op(jax.lax.rsqrt, "rsqrt")
square = unary_op(jnp.square, "square")
abs = unary_op(jnp.abs, "abs")  # noqa: A001
sign = unary_op(jnp.sign, "sign")
neg = unary_op(jnp.negative, "neg")
reciprocal = unary_op(jnp.reciprocal, "reciprocal")
floor = unary_op(jnp.floor, "floor")
ceil = unary_op(jnp.ceil, "ceil")
round = unary_op(jnp.round, "round")  # noqa: A001
trunc = unary_op(jnp.trunc, "trunc")
frac = unary_op(lambda x: x - jnp.trunc(x), "frac")
sin = unary_op(jnp.sin, "sin")
cos = unary_op(jnp.cos, "cos")
tan = unary_op(jnp.tan, "tan")
asin = unary_op(jnp.arcsin, "asin")
acos = unary_op(jnp.arccos, "acos")
atan = unary_op(jnp.arctan, "atan")
sinh = unary_op(jnp.sinh, "sinh")
cosh = unary_op(jnp.cosh, "cosh")
tanh = unary_op(jnp.tanh, "tanh")
asinh = unary_op(jnp.arcsinh, "asinh")
acosh = unary_op(jnp.arccosh, "acosh")
atanh = unary_op(jnp.arctanh, "atanh")
erf = unary_op(jax.scipy.special.erf, "erf")
erfinv = unary_op(jax.scipy.special.erfinv, "erfinv")
sigmoid = unary_op(jax.nn.sigmoid, "sigmoid")
logit = unary_op(jax.scipy.special.logit, "logit")
digamma = unary_op(jax.scipy.special.digamma, "digamma")
lgamma = unary_op(jax.scipy.special.gammaln, "lgamma")
i0 = unary_op(jax.scipy.special.i0, "i0")
i1 = unary_op(jax.scipy.special.i1, "i1")
angle = unary_op(jnp.angle, "angle")
conj = unary_op(jnp.conj, "conj")
real = unary_op(jnp.real, "real")
imag = unary_op(jnp.imag, "imag")
deg2rad = unary_op(jnp.deg2rad, "deg2rad")
rad2deg = unary_op(jnp.rad2deg, "rad2deg")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference ops.yaml 'scale'."""
    x = ensure_tensor(x)
    s = scale._value if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        fn = lambda a: a * s + bias
    else:
        fn = lambda a: (a + bias) * s
    out = dispatch.apply(fn, x, op_name="scale")
    if act == "relu":
        from ..nn import functional as F

        out = F.relu(out)
    return out


def clip(x, min=None, max=None, name=None):  # noqa: A002
    x = ensure_tensor(x)
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return dispatch.apply(lambda a: jnp.clip(a, lo, hi), x, op_name="clip")


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return dispatch.apply(lambda a, b, w: a + w * (b - a), x, y, weight, op_name="lerp")
    return dispatch.apply(lambda a, b: a + weight * (b - a), x, y, op_name="lerp")


def add_n(inputs, name=None):
    """Sum of a list of tensors (reference ops.yaml 'add_n')."""
    if isinstance(inputs, Tensor):
        return inputs
    ts = [ensure_tensor(t) for t in inputs]

    def fn(*raws):
        out = raws[0]
        for r in raws[1:]:
            out = out + r
        return out

    return dispatch.apply(fn, *ts, op_name="add_n")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


def multiplex(inputs, index, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    index = ensure_tensor(index)

    def fn(idx, *raws):
        stacked = jnp.stack(raws, axis=0)
        rows = idx.reshape(-1)
        return stacked[rows, jnp.arange(raws[0].shape[0])]

    return dispatch.apply(fn, index, *ts, op_name="multiplex")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x,
        op_name="nan_to_num",
    )


# ---- tests for nan/inf (nondiff) --------------------------------------------
def isnan(x, name=None):
    return dispatch.apply_nondiff(jnp.isnan, ensure_tensor(x))


def isinf(x, name=None):
    return dispatch.apply_nondiff(jnp.isinf, ensure_tensor(x))


def isfinite(x, name=None):
    return dispatch.apply_nondiff(jnp.isfinite, ensure_tensor(x))


# ---- cumulative --------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    from ..core.dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype is not None else None

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=jd)
        return jnp.cumsum(a, axis=axis, dtype=jd)

    return dispatch.apply(fn, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    from ..core.dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype is not None else None

    def fn(a):
        if dim is None:
            a = a.reshape(-1)
            return jnp.cumprod(a, dtype=jd)
        return jnp.cumprod(a, axis=dim, dtype=jd)

    return dispatch.apply(fn, x, op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ax = axis if axis is not None else 0
    xv = x if axis is not None else dispatch.apply(lambda a: a.reshape(-1), x)
    vals = dispatch.apply(lambda a: jax.lax.cummax(a, axis=ax), xv, op_name="cummax")
    idx = dispatch.apply_nondiff(lambda a: _running_arg(a, ax, jax.lax.cummax), xv)
    return vals, idx


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    ax = axis if axis is not None else 0
    xv = x if axis is not None else dispatch.apply(lambda a: a.reshape(-1), x)
    vals = dispatch.apply(lambda a: jax.lax.cummin(a, axis=ax), xv, op_name="cummin")
    idx = dispatch.apply_nondiff(lambda a: _running_arg(a, ax, jax.lax.cummin), xv)
    return vals, idx


def _running_arg(a, ax, cumfn):
    """Index of the running extremum along ``ax``."""
    cm = cumfn(a, axis=ax)
    isnew = jnp.equal(a, cm)
    idxs = jnp.arange(a.shape[ax]).reshape(
        [-1 if i == ax % a.ndim else 1 for i in range(a.ndim)]
    )
    idxs = jnp.broadcast_to(idxs, a.shape)
    return jax.lax.cummax(jnp.where(isnew, idxs, -1), axis=ax)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return dispatch.apply(
        lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
        x,
        op_name="diff",
    )


# ---- inplace variants (reference: ops with trailing underscore) --------------
def _make_inplace(fn_name):
    import sys

    mod = sys.modules[__name__]

    def inplace(x, *args, **kwargs):
        out = getattr(mod, fn_name)(x, *args, **kwargs)
        x._set_value(out._value)
        x._grad_node = out._grad_node
        x._output_index = out._output_index
        if out._grad_node is not None:
            x.stop_gradient = False
        return x

    inplace.__name__ = fn_name + "_"
    return inplace


add_ = _make_inplace("add")
subtract_ = _make_inplace("subtract")
multiply_ = _make_inplace("multiply")
divide_ = _make_inplace("divide")
scale_ = _make_inplace("scale")
clip_ = _make_inplace("clip")
exp_ = _make_inplace("exp")
sqrt_ = _make_inplace("sqrt")
rsqrt_ = _make_inplace("rsqrt")
floor_ = _make_inplace("floor")
ceil_ = _make_inplace("ceil")
round_ = _make_inplace("round")
reciprocal_ = _make_inplace("reciprocal")
tanh_ = _make_inplace("tanh")


# ---------------------------------------------------------------------------
# long-tail math (reference python/paddle/tensor/math.py: addmm:1979,
# trace:2439, diagonal:2539, trapezoid:5473, frexp:5584, ldexp:5733,
# polygamma:5377, logcumsumexp:3513, sgn:4993, renorm:2202, vander:5519,
# increment:2905; complex helpers as_complex/as_real/polar
# python/paddle/tensor/creation.py:2464)
# ---------------------------------------------------------------------------

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    input, x, y = ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)
    return dispatch.apply(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        input, x, y, op_name="addmm")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        x, op_name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x, op_name="diagonal")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    """Pairwise p-distance between row batches ([..., M, D] × [..., N, D] →
    [..., M, N]).  The p=2 path is the MXU-friendly |x|²+|y|²-2xy form."""
    x, y = ensure_tensor(x), ensure_tensor(y)

    def _safe_sqrt(sq):
        # double-where: subgradient 0 (not inf) where the distance is 0
        pos = sq > 0
        return jnp.where(pos, jnp.sqrt(jnp.where(pos, sq, 1.0)), 0.0)

    def fn(a, b):
        # mm-based euclid form loses ~1e-3 to cancellation in fp32, so (like
        # the reference/torch *_if_necessary mode) only use it when the
        # direct-difference tensor would be large
        big = a.shape[-2] > 25 or b.shape[-2] > 25
        if p == 2.0 and (compute_mode == "use_mm_for_euclid_dist"
                         or ("if_necessary" in compute_mode and big)):
            a2 = jnp.sum(a * a, -1, keepdims=True)          # [..., M, 1]
            b2 = jnp.sum(b * b, -1, keepdims=True)          # [..., N, 1]
            sq = a2 + jnp.swapaxes(b2, -1, -2) - 2 * jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            return _safe_sqrt(jnp.maximum(sq, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), -1)
        if jnp.isinf(p):
            return jnp.max(diff, -1)
        if p == 2.0:
            return _safe_sqrt(jnp.sum(diff * diff, -1))
        pos = diff > 0
        safe = jnp.where(pos, diff, 1.0)
        return jnp.sum(jnp.where(pos, safe ** p, 0.0), -1) ** (1.0 / p)

    return dispatch.apply(fn, x, y, op_name="cdist")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        x = ensure_tensor(x)
        return dispatch.apply(
            lambda yy, xx: jnp.trapezoid(yy, x=xx, axis=axis), y, x,
            op_name="trapezoid")
    return dispatch.apply(
        lambda yy: jnp.trapezoid(yy, dx=1.0 if dx is None else dx, axis=axis),
        y, op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)

    def _cumtrapz(yy, xx=None):
        y1 = jnp.moveaxis(yy, axis, -1)
        if xx is not None:
            d = jnp.diff(jnp.moveaxis(xx, axis, -1) if xx.ndim == yy.ndim else xx, axis=-1)
        else:
            d = 1.0 if dx is None else dx
        avg = (y1[..., 1:] + y1[..., :-1]) / 2.0
        out = jnp.cumsum(avg * d, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    if x is not None:
        x = ensure_tensor(x)
        return dispatch.apply(lambda yy, xx: _cumtrapz(yy, xx), y, x,
                              op_name="cumulative_trapezoid")
    return dispatch.apply(_cumtrapz, y, op_name="cumulative_trapezoid")


def frexp(x, name=None):
    """Decompose into mantissa ∈ [0.5, 1) and integer exponent (both returned
    as float tensors, reference math.py:5584)."""
    x = ensure_tensor(x)

    def fn(a):
        m, e = jnp.frexp(a)
        return m, e.astype(a.dtype)

    return dispatch.apply(fn, x, op_name="frexp")


def ldexp(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return dispatch.apply(
        lambda a, b: (a * jnp.exp2(b.astype(a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32))).astype(
            a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32),
        x, y, op_name="ldexp")


def i0e(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jax.scipy.special.i0e, x, op_name="i0e")


def i1e(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jax.scipy.special.i1e, x, op_name="i1e")


def i0(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jax.scipy.special.i0, x, op_name="i0")


def i1(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jax.scipy.special.i1, x, op_name="i1")


def polygamma(x, n, name=None):
    x = ensure_tensor(x)
    if n == 0:
        return dispatch.apply(jax.scipy.special.digamma, x, op_name="polygamma")
    return dispatch.apply(
        lambda a: jax.scipy.special.polygamma(n, a), x, op_name="polygamma")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.cumlogsumexp(a, axis=ax)

    return dispatch.apply(fn, x, op_name="logcumsumexp")


def sgn(x, name=None):
    """Complex-aware sign: x/|x| (0 where x==0), reference math.py:4993."""
    x = ensure_tensor(x)

    def fn(a):
        if jnp.iscomplexobj(a):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / jnp.where(mag == 0, 1.0, mag))
        return jnp.sign(a)

    return dispatch.apply(fn, x, op_name="sgn")


def polar(abs, angle, name=None):  # noqa: A002
    abs, angle = ensure_tensor(abs), ensure_tensor(angle)
    return dispatch.apply(
        lambda r, t: (r * jnp.cos(t) + 1j * r * jnp.sin(t)).astype(
            jnp.complex128 if r.dtype == jnp.float64 else jnp.complex64),
        abs, angle, op_name="polar")


def as_complex(x, name=None):
    """[..., 2] float → [...] complex (reference creation.py as_complex)."""
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x, op_name="as_complex")


def as_real(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
        x, op_name="as_real")


def renorm(x, p, axis, max_norm, name=None):
    """Clamp the p-norm of every slice along `axis` to max_norm
    (reference math.py:2202)."""
    x = ensure_tensor(x)

    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return dispatch.apply(fn, x, op_name="renorm")


def increment(x, value=1.0, name=None):
    """In-place add on a 1-element tensor (reference math.py:2905)."""
    out = add(x, ensure_tensor(value))
    x._set_value(out._value)
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    if out._grad_node is not None:
        x.stop_gradient = False
    return x


def vander(x, n=None, increasing=False, name=None):
    x = ensure_tensor(x)
    cols = n if n is not None else x.shape[0]
    return dispatch.apply(
        lambda a: jnp.vander(a, N=cols, increasing=increasing),
        x, op_name="vander")


def take(x, index, mode="raise", name=None):  # noqa: A002
    """Flattened gather (reference math.py take). mode 'wrap'/'clip' follow
    numpy; 'raise' clips (no data-dependent errors inside XLA programs)."""
    x, index = ensure_tensor(x), ensure_tensor(index)

    def fn(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx = ((idx % n) + n) % n
        else:
            idx = jnp.clip(jnp.where(idx < 0, idx + n, idx), 0, n - 1)
        return jnp.take(flat, idx.reshape(-1)).reshape(idx.shape)

    return dispatch.apply(fn, x, index, op_name="take")


def squared_l2_norm(x, name=None):
    """reference phi squared_l2_norm (grad-clip helper): sum(x*x) as a
    scalar, accumulated at >= fp32 and returned in the accumulation
    dtype (float64 inputs keep float64, like the kernel's MPDType)."""
    x = ensure_tensor(x)
    return dispatch.apply(
        lambda a: jnp.sum(jnp.square(
            a.astype(jnp.promote_types(a.dtype, jnp.float32)))),
        x, op_name="squared_l2_norm")
