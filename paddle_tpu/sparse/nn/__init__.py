"""sparse.nn: activations over sparse tensors (reference:
python/paddle/sparse/nn/ — ReLU/LeakyReLU/Softmax layers + functional).
Submanifold sparse conv is out of the TPU v1 scope (reference
kernels/sparse/gpu/conv_kernel.cu) — dense conv covers TPU workloads."""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer import Layer as _Layer
from . import functional  # noqa: F401


class ReLU(_Layer):
    def forward(self, x):
        return functional.relu(x)


class LeakyReLU(_Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self._slope)


class Softmax(_Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, self._axis)
