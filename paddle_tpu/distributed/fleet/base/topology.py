"""Hybrid-parallel topology.

Reference: fleet/base/topology.py:54 CommunicateTopology / :140
HybridCommunicateGroup — builds an NCCL group per parallelism axis.
TPU-native: ONE global Mesh with axes (dp, pp, sharding, sep, mp); each
"communicate group" is a mesh-axis view (Group). No communicator bootstrap:
XLA lays collectives on ICI rings from the mesh at compile time.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import numpy as np

from ... import collective as _collective
from ...env import get_rank, get_world_size
from ...group import Group
from ... import mesh as _mesh


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return tuple(np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [
            self.get_rank(**dict(zip(self._parallel_names, coord)))
            for coord in itertools.product(*[range(d) for d in self._dims])
            if coord[axis] == index
        ]
        return sorted(ranks)

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        lists = []
        for other_coord in itertools.product(*[range(d) for d in other_dims]):
            ranks = []
            for k in range(self._dims[axis]):
                coord = list(other_coord)
                coord.insert(axis, k)
                ranks.append(self.get_rank(**dict(zip(self._parallel_names, coord))))
            lists.append(ranks)
        return lists


# paddle axis name → mesh axis name
_AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}


class HybridCommunicateGroup:
    """Reference topology.py:140. Builds the global Mesh and exposes
    per-axis Groups + this process's coordinates."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]

        self._dp_degree = topology.get_dim("data") if "data" in names else 1
        self._pp_degree = topology.get_dim("pipe") if "pipe" in names else 1
        self._sharding_degree = topology.get_dim("sharding") if "sharding" in names else 1
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1
        self._mp_degree = topology.get_dim("model") if "model" in names else 1

        # build the global mesh in the reference's axis order
        axes = {}
        for n, d in zip(names, dims):
            axes[_AXIS_MAP.get(n, n)] = d
        import jax

        n_needed = int(np.prod(dims))
        if n_needed <= len(jax.devices()):
            _mesh.set_mesh(_mesh.build_mesh(axes))
        # groups as axis views
        self._dp_group = Group(("dp",), gid=101)
        self._pp_group = Group(("pp",), gid=102)
        self._sharding_group = Group(("sharding",), gid=103)
        self._sep_group = Group(("sep",), gid=104)
        self._mp_group = Group(("mp",), gid=105)
        self.global_rank = get_rank()

    # --- degrees / ranks (controller view: rank 0 of each axis) ---------
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1:
            return "model"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    # model parallel
    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    @property
    def stage_id(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_pipe_parallel_rank(self):
        return 0

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_p2p_groups(self):
        return None

    # sharding
    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sep
    def get_sep_parallel_rank(self):
        return 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, sharding=False):
        return Group(("dp", "pp", "sharding", "sep", "mp"), gid=110)

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
