"""Preemption-safe exit: SIGTERM/SIGINT -> checkpoint at the next step
boundary, then clean exit.

Reference: fleet elastic's restart contract — the launcher SIGTERMs
workers on membership change and relaunches them; a worker that dies
mid-step loses everything since its last save.  On TPU pods preemption is
routine (maintenance events deliver SIGTERM with a grace window), so the
handler converts the signal into a *request flag* the training loop polls
at step boundaries: the step in flight completes, the state is saved
crash-consistently, and the process exits 0 so the launcher restarts it
into ``resume``.

Also plugs into ElasticManager: ``handler.as_elastic_on_change()`` is an
``on_change`` callback (membership shrank -> checkpoint-then-exit, the
restart side of the manager's contract).
"""
from __future__ import annotations

import signal
import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["PreemptionHandler", "GracefulExit"]

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class GracefulExit(SystemExit):
    """Raised (with code 0) by checkpoint_and_exit once the state is on
    disk — a clean exit the launcher treats as restartable."""

    def __init__(self):
        super().__init__(0)


class PreemptionHandler:
    def __init__(self, signals=_DEFAULT_SIGNALS):
        self._signals = tuple(signals)
        self._requested = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._installed = False

    # -- lifecycle -------------------------------------------------------
    def install(self):
        """Install signal handlers (main thread only — Python's signal
        contract).  Idempotent; pairs with uninstall()."""
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_signal(self, signum, frame):
        self._requested.set()

    # -- request surface -------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def request(self, *_args, **_kw):
        """Programmatic preemption request (same path the signals take).
        Accepts and ignores arguments so it can sit directly behind
        callback contracts."""
        self._requested.set()

    def clear(self):
        self._requested.clear()

    def as_elastic_on_change(self) -> Callable:
        """An ElasticManager ``on_change`` callback: any membership change
        requests checkpoint-then-clean-exit at the next step boundary (the
        relaunch brings this worker back with the rescaled spec)."""
        return self.request

    # -- step-boundary service ------------------------------------------
    def checkpoint_and_exit_if_requested(self, manager, train_state,
                                         step: int, epoch: int = 0,
                                         position: Optional[dict] = None):
        """Poll at a step boundary: when a preemption was requested, save
        synchronously (the process is about to die — async gains nothing)
        and raise GracefulExit(0).  No-op otherwise."""
        if not self.requested:
            return
        pos = dict(position or {})
        pos.setdefault("epoch", epoch)
        pos.setdefault("step", step)
        manager.save(train_state.capture(position=pos), step=step,
                     epoch=epoch, meta={"preempted": True}, blocking=True)
        raise GracefulExit()
