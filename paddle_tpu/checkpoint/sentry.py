"""Bad-step sentry: one fused all-finite reduction over the grad pytree.

The reference detects loss-scale overflow with check_finite_and_unscale
(paddle/phi/kernels/check_finite_and_unscale_kernel.cu) — ONE kernel over
all grads.  The eager analog here had degraded to a Python loop with one
``bool(jnp.isfinite(g).all())`` host sync PER GRADIENT; this module
restores the fused design: a single jitted reduction over the whole list
(jit caches per shape/dtype structure, so steady-state training reuses one
compiled program and pays exactly one host sync).

``BadStepSentry`` builds skip/rollback policy on top: non-finite steps are
skipped and counted, and after N consecutive bad steps the training state
is rolled back to the last valid checkpoint.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

__all__ = ["all_finite", "tree_all_finite", "unscale_and_check",
           "BadStepSentry"]


@jax.jit
def tree_all_finite(leaves):
    """Fused finiteness reduction over a pytree of arrays — one scalar
    bool out, no per-leaf host syncs.  Non-float leaves (int/bool indices
    riding in the tree) are finite by construction and skipped at trace
    time."""
    flags = [jnp.isfinite(l).all() for l in jax.tree_util.tree_leaves(leaves)
             if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not flags:
        return jnp.asarray(True)
    return functools.reduce(jnp.logical_and, flags)


def all_finite(values) -> bool:
    """Host-side convenience: True iff every float leaf in ``values``
    (Tensors, arrays, nested containers) is finite.  Exactly one device
    round-trip regardless of how many leaves."""
    from ..tensor import Tensor

    leaves = [v._value if isinstance(v, Tensor) else v
              for v in jax.tree_util.tree_leaves(
                  values, is_leaf=lambda x: isinstance(x, Tensor))]
    if not leaves:
        return True
    return bool(tree_all_finite(leaves))


@jax.jit
def unscale_and_check(grads, scale):
    """GradScaler.unscale_ fused body: multiply every grad by 1/scale in
    fp32, cast back to each grad's dtype, and reduce finiteness of the
    SCALED fp32 values into one flag.  Returns (new_grads, finite_flag)."""
    inv = 1.0 / scale.astype(jnp.float32)
    scaled = [g.astype(jnp.float32) * inv for g in grads]
    flags = [jnp.isfinite(s).all() for s in scaled]
    finite = functools.reduce(jnp.logical_and, flags) if flags else jnp.asarray(True)
    return [s.astype(g.dtype) for s, g in zip(scaled, grads)], finite


class BadStepSentry:
    """Skip non-finite optimizer steps; roll back after a run of them.

    Usage (raw loop)::

        sentry = BadStepSentry(manager=mgr, train_state=ts, max_consecutive_bad=3)
        loss.backward()
        sentry.guard_step(opt)      # steps only when all grads are finite
        opt.clear_grad()

    ``guard_step`` costs one fused device reduction + one host sync — the
    same price GradScaler already pays for dynamic loss scaling.  On
    rollback the last VALID checkpoint is restored through
    (manager, train_state), or a custom ``on_rollback`` callback runs.
    """

    def __init__(self, max_consecutive_bad: int = 3, manager=None,
                 train_state=None,
                 on_rollback: Optional[Callable[[], Any]] = None):
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        self.max_consecutive_bad = max_consecutive_bad
        self.manager = manager
        self.train_state = train_state
        self.on_rollback = on_rollback
        self.stats = {"steps": 0, "good_steps": 0, "bad_steps": 0,
                      "consecutive_bad": 0, "rollbacks": 0}

    def _grads(self, optimizer) -> List[Any]:
        return [p.grad._value for p in optimizer._parameter_list
                if p.grad is not None]

    def grads_finite(self, optimizer) -> bool:
        grads = self._grads(optimizer)
        if not grads:
            return True
        return bool(tree_all_finite(grads))

    def guard_step(self, optimizer) -> bool:
        """optimizer.step() iff the grad pytree is all-finite; returns
        whether the step was applied.  Counts bad steps and triggers
        rollback after ``max_consecutive_bad`` in a row."""
        self.stats["steps"] += 1
        if self.grads_finite(optimizer):
            self.stats["good_steps"] += 1
            self.stats["consecutive_bad"] = 0
            optimizer.step()
            return True
        self.stats["bad_steps"] += 1
        self.stats["consecutive_bad"] += 1
        if self.stats["consecutive_bad"] >= self.max_consecutive_bad:
            self.rollback()
        return False

    def rollback(self):
        """Restore the last valid checkpoint (or run on_rollback)."""
        self.stats["consecutive_bad"] = 0
        if self.on_rollback is not None:
            self.on_rollback()
            self.stats["rollbacks"] += 1
            return
        if self.manager is None or self.train_state is None:
            return
        info = self.manager.latest()
        if info is None:
            return
        tree, _ = self.manager.restore(info)
        self.train_state.restore(tree)
        self.stats["rollbacks"] += 1
