"""SPMD pipeline parallelism over the 'pp' mesh axis.

Reference: fleet/meta_parallel/pipeline_parallel.py:229 (1F1B schedule with
batched NCCL isend/irecv in pp_utils/p2p_communication.py) and the
FleetExecutor interceptor runtime (fleet_executor/carrier.h:50).

TPU-native redesign: there are no per-rank processes or p2p sockets.
The whole pipeline is ONE jitted SPMD program:

- The L homogeneous blocks' parameters are STACKED along a leading axis
  ([L, ...]) and sharded over 'pp', so each pipeline stage holds its
  contiguous slice of layers in HBM — the analog of PipelineLayer's
  segment partitioning (pp_layers.py:239).
- Execution runs under ``jax.shard_map`` with only 'pp' manual (dp/sp/mp
  stay auto, so GSPMD still partitions the tensor-parallel math inside
  each stage). Microbatch activations rotate between neighbouring stages
  with ``lax.ppermute`` over ICI — the collective-permute analog of the
  reference's isend/irecv pairs — in a ``lax.scan`` over
  T = n_micro + n_stages - 1 ticks (the GPipe wavefront; XLA overlaps the
  reverse pass, giving 1F1B-class utilisation without a hand-written
  interleaved schedule).
- Backward needs no code: ppermute/scan/psum all transpose, so jax.vjp
  of the pipelined forward IS the pipelined backward.

Without a pp axis (or pp=1) the same stacked layout runs as a plain
``lax.scan`` over layers — which also compiles the block body once
instead of L times (a large compile-time win over unrolled dygraph).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ... import mesh as _mesh
from ....core import compat as _compat

__all__ = ["scan_blocks", "pipeline_blocks", "stacked_param_sharding"]


def stacked_param_sharding(shape, pp_axis="pp"):
    """NamedSharding for a stacked [L, ...] parameter: leading dim over 'pp'."""
    mesh = _mesh.get_mesh()
    if pp_axis in mesh.axis_names and mesh.shape[pp_axis] > 1:
        return NamedSharding(mesh, PartitionSpec(pp_axis, *([None] * (len(shape) - 1))))
    return NamedSharding(mesh, PartitionSpec())


def _checkpoint(fn, policy):
    """jax.checkpoint with a named rematerialisation policy.

    None/"full" recomputes everything (min residency); "dots" saves MXU
    outputs and recomputes only VPU work (near-free backward recompute);
    "dots_saveable" additionally saves batched dots.
    """
    if policy in (None, "full"):
        return jax.checkpoint(fn)
    policies = {
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
    }
    if policy not in policies:
        raise ValueError(
            f"unknown remat policy {policy!r}; expected one of "
            f"{['full', *policies]}")
    return jax.checkpoint(fn, policy=policies[policy])


def scan_blocks(block_fn: Callable, stacked: Sequence, x, *, remat: bool = False,
                remat_policy: str | None = None, remat_interval: int = 1):
    """Run L stacked homogeneous blocks sequentially: x -> block(p_i, x).

    ``block_fn(params_tuple, x) -> y`` with params_tuple holding one
    layer's slices. ``stacked`` is a tuple of [L, ...] arrays.

    ``remat_interval`` groups the rematerialisation boundary: ``k > 1``
    reshapes the stacked leading dim to [L/k, k, ...] and checkpoints a
    k-block group body, so backward saves only every k-th block boundary
    (1/k the saved residuals) at the cost of k blocks' activations live
    during each group's recompute.  Identical math to ``k == 1`` — same
    block sequence, each block recomputed exactly once — so the
    (interval, policy) pair is a pure memory/locality trade the measured
    autotune search can explore (docs/training_perf.md).  Requires
    ``L % k == 0``.
    """
    k = int(remat_interval) if remat else 1
    if k <= 1:
        body = _checkpoint(block_fn, remat_policy) if remat else block_fn

        def step(h, params):
            return body(params, h), None

        out, _ = jax.lax.scan(step, x, tuple(stacked))
        return out

    L = int(np.shape(stacked[0])[0])
    if L % k != 0:
        raise ValueError(
            f"remat_interval={k} must divide the stacked layer count {L}")

    def group(params_group, h):
        # k consecutive blocks under ONE checkpoint boundary
        def inner(carry, params):
            return block_fn(params, carry), None

        h2, _ = jax.lax.scan(inner, h, params_group)
        return h2

    gbody = _checkpoint(group, remat_policy)
    grouped = tuple(a.reshape((L // k, k) + tuple(a.shape[1:]))
                    for a in stacked)

    def step(h, params_group):
        return gbody(params_group, h), None

    out, _ = jax.lax.scan(step, x, grouped)
    return out


def pipeline_blocks(block_fn: Callable, stacked: Sequence, x_micro, *,
                    layers_per_stage: int, pp_axis: str = "pp",
                    remat: bool = False, remat_policy: str | None = None,
                    block_takes_index: bool = False,
                    n_virtual: int = 1):
    """Microbatch-pipelined execution of stacked blocks over the pp axis.

    Args:
      block_fn: (params_tuple, h) -> h for ONE block; with
        ``block_takes_index`` it is (params_tuple, h, mb_idx) -> h, letting
        stochastic blocks (dropout) decorrelate across microbatches.
      stacked: tuple of [L, ...] arrays, L = n_stages * layers_per_stage,
        leading dim sharded over ``pp_axis``.
      x_micro: [M, mb, ...] microbatched input activations (replicated over
        ``pp_axis``; may be sharded over dp/sp on inner dims).
      layers_per_stage: L // n_stages.
      n_virtual: virtual pipeline stages per device (reference
        PipelineParallelWithInterleave, pipeline_parallel.py:625).  Layers
        are assigned to devices round-robin by chunk (chunk c -> device
        c % S, Megatron interleave layout) and microbatches make
        ``n_virtual`` trips around the ring; the fill/drain bubble drops
        from (S-1)/(M+S-1) to (S-1)/(V*M+S-1).  Requires M >= S so phase
        v+1's first tick never outruns phase v's drain.

    Returns [M, mb, ...] outputs (replicated over the pp axis).

    Memory note (1F1B-class residency): with ``remat=True`` each tick's
    stage execution saves only its carry ([mb, ...] activation) and
    recomputes block internals in backward, so per-device residency is
    O(ticks x microbatch-activation) — the same order 1F1B buys the
    reference, achieved here by remat instead of schedule gymnastics.
    """
    mesh = _mesh.get_mesh()
    n_stages = mesh.shape[pp_axis]
    n_micro = x_micro.shape[0]
    V = int(n_virtual)
    if V > 1:
        if n_micro < n_stages:
            raise ValueError(
                f"interleave needs n_micro ({n_micro}) >= n_stages "
                f"({n_stages})")
        if layers_per_stage % V != 0:
            raise ValueError(
                f"layers_per_stage ({layers_per_stage}) must be divisible "
                f"by n_virtual ({V})")
    if not block_takes_index:
        base = block_fn
        block_fn = lambda p, h, idx: base(p, h)  # noqa: E731
    body = _checkpoint(block_fn, remat_policy) if remat else block_fn

    lpc = layers_per_stage // V  # layers per virtual chunk

    if V > 1:
        # Megatron interleave layout: chunk c -> device c % S.  Re-order the
        # stacked leading dim so each device's rows are contiguous:
        # device d holds chunks d, S+d, 2S+d, ... (V chunks of lpc layers).
        order = np.concatenate([
            np.arange((v * n_stages + d) * lpc, (v * n_stages + d + 1) * lpc)
            for d in range(n_stages) for v in range(V)
        ])
        stacked = tuple(a[order] for a in stacked)

    def chunk_scan(local_params, h, mb_idx, v_idx):
        """Run the local virtual chunk ``v_idx`` (lpc layers)."""
        if V == 1:
            chunk = local_params
        else:
            chunk = tuple(
                jax.lax.dynamic_slice_in_dim(p, v_idx * lpc, lpc, axis=0)
                for p in local_params
            )

        def step(carry, params):
            return body(params, carry, mb_idx), None

        out, _ = jax.lax.scan(step, h, chunk)
        return out

    def spmd(stacked_local, x_local):
        stage = jax.lax.axis_index(pp_axis)
        is_last_dev = stage == n_stages - 1

        # zeros are pp-invariant; the scan carry becomes pp-varying (each
        # stage computes different activations), so pcast the initial carry
        varying = lambda z: _compat.pcast(z, (pp_axis,), to="varying")  # noqa: E731
        # zeros from shape, not zeros_like(x_local[0]): indexing would
        # trace a dead slice+squeeze of the input (GL005)
        state = varying(jnp.zeros(x_local.shape[1:], x_local.dtype))
        outputs = varying(jnp.zeros_like(x_local))
        # phase-wrap buffer (interleave only): device 0 parks activations
        # returning from the last device until their next trip starts
        # dtype pinned: bare zeros(()) is f64 under x64 mode and would ride
        # the whole tick-scan carry (GL001 x64-leak)
        inbuf = (varying(jnp.zeros_like(x_local)) if V > 1
                 else jnp.zeros((), x_local.dtype))

        total_ticks = V * n_micro + n_stages - 1

        def tick(carry, t):
            state, inbuf, outputs = carry
            rel = t - stage
            active = (rel >= 0) & (rel < V * n_micro)
            v_idx = jnp.clip(rel // n_micro, 0, V - 1)
            mb_idx = jnp.clip(rel % n_micro, 0, n_micro - 1)
            # stage 0 feeds from x (trip 0) or the phase-wrap buffer
            # (later trips); other stages consume the rotated carry
            if V == 1:
                entry = x_local[mb_idx]
            else:
                entry = jnp.where(v_idx == 0, x_local[mb_idx], inbuf[mb_idx])
            inp = jnp.where(stage == 0, entry, state)
            y = chunk_scan(stacked_local, inp, mb_idx, v_idx)
            y = jnp.where(active, y, jnp.zeros_like(y))
            done = active & is_last_dev & (v_idx == V - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(done, y, outputs[mb_idx]), mb_idx, 0)
            # rotate activations to the next device (ICI collective-permute)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            if V > 1:
                # park arrivals from the ring's wrap (sender = prev device,
                # who processed rel' = t - (d-1 mod S) this tick) for the
                # next trip; only stage 0's buffer is ever read
                s_rel = t - ((stage - 1) % n_stages)
                s_active = (s_rel >= 0) & (s_rel < V * n_micro)
                s_mb = jnp.clip(s_rel % n_micro, 0, n_micro - 1)
                park = s_active & (stage == 0)
                inbuf = jax.lax.dynamic_update_index_in_dim(
                    inbuf, jnp.where(park, nxt, inbuf[s_mb]), s_mb, 0)
            return (nxt, inbuf, outputs), None

        (_, _, outputs), _ = jax.lax.scan(
            tick, (state, inbuf, outputs), jnp.arange(total_ticks)
        )
        # replicate the last stage's outputs across pp so downstream (loss)
        # code sees a normal replicated activation
        outputs = jax.lax.psum(
            jnp.where(is_last_dev, outputs, jnp.zeros_like(outputs)), pp_axis
        )
        return outputs

    nd = lambda a: (None,) * (a.ndim - 1)  # noqa: E731
    in_specs = (
        tuple(PartitionSpec(pp_axis, *nd(s)) for s in stacked),
        PartitionSpec(),  # microbatches replicated over pp (dp/sp stay auto)
    )
    fn = _compat.shard_map(
        spmd,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=PartitionSpec(),
        axis_names=frozenset({pp_axis}),
    )
    return fn(tuple(stacked), x_micro)
