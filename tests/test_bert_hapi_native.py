"""BERT (BASELINE config 1), hapi Model, vision models (config 0), and the
native TCPStore (reference test analogs: test/dygraph_to_static/test_bert.py,
hapi tests, phi/core/distributed/store/test_tcp_store.cc)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.bert import (
    BertForPretraining,
    BertPretrainingCriterion,
    bert_tiny,
)


def _bert_batch(cfg, b=2, s=16, n_mask=4, seed=0):
    rng = np.random.RandomState(seed)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)), dtype="int64")
    seg = pt.to_tensor(rng.randint(0, 2, (b, s)), dtype="int64")
    pos = pt.to_tensor(rng.randint(0, s, (b, n_mask)), dtype="int64")
    mlm_labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (b, n_mask)), dtype="int64")
    nsp = pt.to_tensor(rng.randint(0, 2, (b,)), dtype="int64")
    return ids, seg, pos, mlm_labels, nsp


def test_bert_pretraining_trains():
    cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    pt.seed(0)
    m = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    ids, seg, pos, mlm_labels, nsp = _bert_batch(cfg)
    losses = []
    for _ in range(3):
        mlm_logits, nsp_logits = m(ids, token_type_ids=seg, masked_positions=pos)
        loss = crit(mlm_logits, nsp_logits, mlm_labels, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_to_static_matches_eager():
    """BASELINE config 1: BERT dygraph_to_static numeric parity."""
    cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    crit = BertPretrainingCriterion()
    ids, seg, pos, mlm_labels, nsp = _bert_batch(cfg)

    pt.seed(9)
    m1 = BertForPretraining(cfg)
    pt.seed(9)
    m2 = BertForPretraining(cfg)
    o1 = pt.optimizer.SGD(learning_rate=1e-2, parameters=m1.parameters())
    o2 = pt.optimizer.SGD(learning_rate=1e-2, parameters=m2.parameters())

    def step(m, o):
        mlm_logits, nsp_logits = m(ids, token_type_ids=seg, masked_positions=pos)
        loss = crit(mlm_logits, nsp_logits, mlm_labels, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    static_step = pt.jit.to_static(lambda: step(m2, o2))
    eager, static = [], []
    for _ in range(4):
        eager.append(float(step(m1, o1)))
        static.append(float(static_step()))
    np.testing.assert_allclose(eager, static, rtol=2e-4, atol=2e-5)


def test_hapi_model_fit():
    from paddle_tpu.hapi import Model
    from paddle_tpu.nn.modules.common import Linear
    from paddle_tpu.nn.modules.container import Sequential
    import paddle_tpu.nn.functional as F

    pt.seed(0)
    net = Sequential(Linear(8, 16), Linear(16, 2))

    class XentLoss(pt.nn.Layer):
        def forward(self, logits, label):
            return F.cross_entropy(logits, label)

    model = Model(net)
    model.prepare(
        optimizer=pt.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters()),
        loss=XentLoss(),
    )
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int64)
    data = [(x, y)] * 6
    hist = model.fit(data, epochs=1, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    ev = model.evaluate(data[:2])
    assert np.isfinite(ev["eval_loss"])
    assert model.summary()["total_params"] == 8 * 16 + 16 + 16 * 2 + 2


@pytest.mark.slow
def test_vision_resnet_builds_and_lenet_trains():
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu.vision.models.lenet import LeNet
    import paddle_tpu.nn.functional as F

    # config 0 parity: resnet50 constructs and runs forward
    pt.seed(0)
    r50 = resnet50(num_classes=10)
    x = pt.to_tensor(np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32))
    logits = r50(x)
    assert logits.shape == [1, 10]

    # small CNN end-to-end training
    net = LeNet(num_classes=4)
    opt = pt.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    rng = np.random.RandomState(0)
    imgs = pt.to_tensor(rng.randn(4, 1, 28, 28).astype(np.float32))
    labels = pt.to_tensor(rng.randint(0, 4, (4,)), dtype="int64")
    losses = []
    for _ in range(4):
        loss = F.cross_entropy(net(imgs), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_native_tcp_store():
    from paddle_tpu.core.native.tcp_store import TCPStore

    master = TCPStore(port=29891, is_master=True)
    master.set("k", b"v1")
    assert master.get("k") == b"v1"
    assert master.add("n", 3) == 3
    assert master.add("n", 4) == 7
    assert master.check("k") and not master.check("missing")

    results = []

    def worker(i):
        c = TCPStore(port=29891)
        c.barrier("b", 3)
        results.append(i)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(results) == [0, 1, 2]

    got = []

    def getter():
        got.append(TCPStore(port=29891).get("late"))

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.2)
    master.set("late", b"ok")
    t.join(timeout=10)
    assert got == [b"ok"]


def test_hapi_metrics_and_plateau_callback():
    """Round-5 verdict item 10: Model.fit/evaluate integrate the metric
    family and the ReduceLROnPlateau callback adjusts the optimizer lr."""
    import paddle_tpu as pt
    from paddle_tpu.hapi import ReduceLROnPlateau
    from paddle_tpu.hapi.model import Model

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.GELU(),
                           pt.nn.Linear(16, 4))
    model = Model(net)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    model.prepare(optimizer=opt, loss=pt.nn.CrossEntropyLoss(),
                  metrics=pt.metric.Accuracy())
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,)).astype(np.int64)
    data = [(x, y)] * 3

    res = model.evaluate(data, verbose=0)
    assert "eval_acc" in res and 0.0 <= res["eval_acc"] <= 1.0

    # plateau callback against the REAL optimizer: a non-improving
    # monitor value must cut the lr after `patience` evaluations
    cb = ReduceLROnPlateau(monitor="eval_loss", factor=0.5, patience=1,
                           verbose=0)
    cb.set_model(model)
    cb.on_eval_end({"eval_loss": 1.0})   # sets best
    cb.on_eval_end({"eval_loss": 1.0})   # plateau -> cut
    assert abs(float(opt.get_lr()) - 0.05) < 1e-9
    # fit() wires callbacks through eval logs end to end
    model.fit(train_data=data, eval_data=data, epochs=1, verbose=0,
              callbacks=[cb])


def test_hapi_amp_configs_train():
    import paddle_tpu as pt
    from paddle_tpu.hapi.model import Model

    pt.seed(1)
    net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.GELU(),
                           pt.nn.Linear(16, 4))
    model = Model(net)
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=net.parameters())
    model.prepare(optimizer=opt, loss=pt.nn.CrossEntropyLoss(),
                  amp_configs="O1")
    rng = np.random.RandomState(0)
    data = [(rng.randn(8, 8).astype(np.float32),
             rng.randint(0, 4, (8,)).astype(np.int64))] * 4
    hist = model.fit(train_data=data, epochs=2, verbose=0)
    assert np.isfinite(hist["loss"]).all()
    assert hist["loss"][-1] < hist["loss"][0]


def test_hapi_tuple_metric_and_dp_mesh_fit():
    """Review fixes: tuple-returning metrics (Precision) unpack into
    update(), and distributed fit shards rank-1 labels without crashing."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.distributed import mesh as M
    from paddle_tpu.hapi.model import Model

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 1))
    model = Model(net)
    opt = pt.optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    model.prepare(optimizer=opt,
                  loss=pt.nn.BCEWithLogitsLoss(),
                  metrics=pt.metric.Precision())
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = (rng.rand(16, 1) > 0.5).astype(np.float32)
    data = [(x, y)] * 2
    res = model.evaluate(data, verbose=0)
    assert "eval_precision" in res

    if len(jax.devices()) >= 8:
        prev = M._global_mesh
        try:
            M.set_mesh(M.build_mesh({"dp": 8}))
            pt.seed(0)
            net2 = pt.nn.Sequential(pt.nn.Linear(8, 4))
            m2 = Model(net2)
            opt2 = pt.optimizer.SGD(learning_rate=0.05,
                                    parameters=net2.parameters())
            m2.prepare(optimizer=opt2, loss=pt.nn.CrossEntropyLoss())
            yb = rng.randint(0, 4, (16,)).astype(np.int64)  # rank-1 labels
            hist = m2.fit(train_data=[(x, yb)] * 3, epochs=1, verbose=0)
            assert np.isfinite(hist["loss"]).all()
        finally:
            M._global_mesh = prev
