"""Optimizer base (reference: python/paddle/optimizer/optimizer.py).

TPU-native: parameter updates are pure jax expressions applied under
``no_grad``; each ``step()`` rebinds param values (``_set_value``), which the
jit tracer functionalizes — so a whole train step (fwd+bwd+update) compiles
into one XLA program with fused optimizer kernels (the analog of the
reference's multi_tensor/fused adam paths, phi/kernels/fused_adam_kernel.cu).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..ops import dispatch
from ..tensor import Parameter, Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
    ):
        if parameters is None:
            raise ValueError("paddle_tpu optimizers require an explicit parameter list")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float):
            self._weight_decay = weight_decay
        elif weight_decay is None:
            self._weight_decay = None
        else:  # L1Decay/L2Decay objects
            self._weight_decay = weight_decay
        self._accumulators: Dict[str, Dict[int, Tensor]] = {}
        self._aux_state: Dict[int, Tensor] = {}
        # eagerly create per-param state so jit capture sees it as
        # pre-existing (the reference creates accumulators lazily in C++)
        self._create_accumulators(self._parameter_list)

    # -- state -------------------------------------------------------------
    def _create_accumulators(self, params):
        pass  # subclasses allocate moments here

    def _add_accumulator(self, name, param, fill_value=0.0, dtype=None):
        store = self._accumulators.setdefault(name, {})
        if id(param) not in store:
            import jax

            acc_raw = jnp.full(param._value.shape, fill_value, dtype or jnp.float32)
            # moments inherit the PARAM's MESH layout by default (a
            # TP-sharded weight gets TP-sharded moments — the memory layout
            # the reference's distributed optimizers maintain by
            # construction).  Single-device placements are NOT inherited:
            # committing moments to one device would poison later mixing
            # with mesh-wide values.
            psh = getattr(param._value, "sharding", None)
            if (psh is not None and isinstance(param._value, jax.Array)
                    and isinstance(psh, jax.sharding.NamedSharding)):
                acc_raw = jax.device_put(acc_raw, psh)
            acc = Tensor(acc_raw)
            # group_sharded (ZeRO) installs this to lay new optimizer
            # state out sharded at creation time (accumulators are lazy,
            # so sharding must hook creation, not just existing state)
            hook = getattr(self, "_accumulator_layout_hook", None)
            if hook is not None:
                hook(acc, param)
            store[id(param)] = acc
        return store[id(param)]

    def _get_accumulator(self, name, param):
        return self._accumulators[name][id(param)]

    # -- layout-preserving param writes -------------------------------------
    def _record_param_layouts(self):
        """Remember each param's concrete sharding so updates can't silently
        change its layout (e.g. ZeRO stage-1 sharded moments would otherwise
        leak their layout into the param through the update expression)."""
        import jax

        if getattr(self, "_param_layouts", None) is None:
            self._param_layouts = {}
        from ..distributed import mesh as _mesh

        for p in self._parameter_list:
            v = p._value
            if id(p) not in self._param_layouts and isinstance(v, jax.Array):
                sh = v.sharding
                # a param still on its creation device counts as REPLICATED
                # once a mesh is active — committing it single-device would
                # make later mixing with mesh-sharded state illegal
                if (_mesh.has_mesh()
                        and isinstance(sh, jax.sharding.SingleDeviceSharding)
                        and len(_mesh.get_mesh().devices.flat) > 1):
                    sh = jax.sharding.NamedSharding(
                        _mesh.get_mesh(), jax.sharding.PartitionSpec())
                self._param_layouts[id(p)] = sh

    def _write_param(self, p, val):
        """Rebind a param value, re-constraining to its recorded layout."""
        import jax

        sh = getattr(self, "_param_layouts", {}).get(id(p))
        if sh is not None:
            from ..jit.api import in_tracing

            if in_tracing():
                val = jax.lax.with_sharding_constraint(val, sh)
            elif getattr(val, "sharding", None) != sh:
                val = jax.device_put(val, sh)
        p._set_value(val)

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("set_lr cannot override an LRScheduler")
        self._learning_rate = float(value)

    def _lr_value(self):
        """lr as a Tensor read through note_read so jit captures scheduler
        changes as a traced input rather than a baked constant."""
        if isinstance(self._learning_rate, LRScheduler):
            t = self._learning_rate._lr_tensor()
            dispatch.note_read(t)
            return t._value
        return self.get_lr()

    # -- step --------------------------------------------------------------
    def _collect_params_grads(self):
        self._record_param_layouts()
        pg = []
        for p in self._parameter_list:
            if isinstance(p, Parameter) and not p.trainable:
                continue
            if p.grad is None:
                pg.append((p, None))
            else:
                pg.append((p, p.grad))
        return pg

    @dispatch.no_grad()
    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads() if g is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            dispatch.note_read(p)
            self._apply_one(p, g)

    def _apply_one(self, p: Tensor, g: Tensor):
        raise NotImplementedError

    def _decayed_grad(self, p, g_raw):
        """Regularization folded into the gradient (reference: regularizer
        appended in _create_optimization_pass).  Floats and L2Decay add
        ``coeff * p``; L1Decay adds ``coeff * sign(p)`` — regularizer
        objects are callables on the raw parameter value."""
        wd = self._weight_decay
        if wd is None:
            return g_raw
        if isinstance(wd, (int, float)):
            return g_raw + float(wd) * p._value
        if callable(wd):
            return g_raw + wd(p._value)
        coeff = getattr(wd, "_coeff", None)
        if coeff is not None:
            return g_raw + coeff * p._value
        return g_raw

    def clear_grad(self, set_to_zero=False):
        """Drop (or zero) accumulated gradients (reference
        Optimizer.clear_grad / clear_gradients).  ``set_to_zero=True``
        writes a zeros-like gradient instead of unbinding — the next
        backward ACCUMULATES into it (reference set_to_zero semantics,
        where the grad tensor keeps its buffer); params that never had a
        grad stay grad-less either way."""
        for p in self._parameter_list:
            if set_to_zero and p.grad is not None:
                # in place: cached references to the grad Tensor see zeros
                p.grad._set_value(jnp.zeros_like(p.grad._value))
            else:
                p.grad = None

    clear_gradients = clear_grad

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        sd = {}
        for name, store in self._accumulators.items():
            for i, p in enumerate(self._parameter_list):
                if id(p) in store:
                    sd[f"{name}_{i}"] = store[id(p)]
        # fp32 master weights (multi_precision Adam/AdamW): without these a
        # resumed bf16 run would re-seed masters from the ROUNDED bf16
        # params, silently re-quantizing the fp32 trajectory mid-training
        for i, p in enumerate(self._parameter_list):
            m = getattr(self, "_master", {}).get(id(p))
            if m is not None:
                sd[f"master_{i}"] = m
        for k, t in self._aux_state.items():
            sd[f"aux_{k}"] = t
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        for name, store in self._accumulators.items():
            for i, p in enumerate(self._parameter_list):
                key = f"{name}_{i}"
                if id(p) in store and key in state_dict:
                    v = state_dict[key]
                    store[id(p)]._set_value(
                        v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    )
        for i, p in enumerate(self._parameter_list):
            m = getattr(self, "_master", {}).get(id(p))
            key = f"master_{i}"
            if m is not None and key in state_dict:
                v = state_dict[key]
                m._set_value(
                    v._value if isinstance(v, Tensor) else jnp.asarray(v))
        # aux scalars (Adam/Adamax beta-power accumulators): state_dict()
        # always saved these, but restore dropped them — a resumed Adam run
        # silently restarted bias correction at t=0, breaking deterministic
        # resume.
        for k, t in self._aux_state.items():
            key = f"aux_{k}"
            if key in state_dict:
                v = state_dict[key]
                t._set_value(
                    v._value if isinstance(v, Tensor) else jnp.asarray(v))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
