"""fleet: user-facing distributed API (reference: fleet/fleet.py —
fleet.init:167, distributed_model fleet/model.py:30,
distributed_optimizer)."""
from __future__ import annotations

from typing import Optional

from ..env import get_rank, get_world_size, init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import meta_parallel  # noqa: F401
from .meta_parallel import PipelineLayer, PipelineParallel, TensorParallel  # noqa: F401
from .recompute import recompute  # noqa: F401

_fleet_initialized = False
_user_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    """fleet.init (reference fleet/fleet.py:167): build the hybrid topology
    mesh from strategy.hybrid_configs."""
    global _fleet_initialized, _user_strategy
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _user_strategy = strategy
    hc = strategy.hybrid_configs
    order = hc.get("order", ["dp", "pp", "sharding", "sep", "mp"])
    name_map = {"dp": "data", "pp": "pipe", "sharding": "sharding", "sep": "sep", "mp": "model"}
    names = [name_map[o] for o in order]
    dims = [int(hc.get(f"{o}_degree", 1)) for o in order]
    topo = CommunicateTopology(names, dims)
    hcg = HybridCommunicateGroup(topo)
    set_hybrid_communicate_group(hcg)
    _fleet_initialized = True
    return None


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


def distributed_model(model):
    """fleet/model.py:30: wrap by strategy — PP > TP > sharding > DP."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return model
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        strat = _user_strategy or DistributedStrategy()
        return PipelineParallel(model, hcg, strat)
    if mode == "model":
        return TensorParallel(model, hcg)
    from ..parallel import DataParallel

    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    from .meta_optimizers import apply_strategy_meta_optimizers
    from .meta_parallel.hybrid_optimizer import HybridParallelOptimizer

    strategy = strategy or _user_strategy
    # meta-optimizer selection pass (reference meta_optimizer_factory):
    # lars/dgc/localsgd strategy flags wrap the inner optimizer
    optimizer = apply_strategy_meta_optimizers(optimizer, strategy)
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return optimizer
    return HybridParallelOptimizer(optimizer, hcg, strategy)


# PS-era APIs kept for surface parity (reference fleet.py server methods)
def init_server(*args, **kwargs):
    raise NotImplementedError("parameter-server mode is out of the TPU scope")


def run_server():
    raise NotImplementedError("parameter-server mode is out of the TPU scope")


def stop_worker():
    pass


def barrier_worker():
    from ..collective import barrier

    barrier()


def save_model(path, mode=0):
    raise NotImplementedError("use paddle_tpu.save(model.state_dict(), path)")


utils = None
