"""Global prefix cache: copy-on-write shared KV pages behind a radix index.

Most production prompts share a prefix (system prompts, few-shot headers,
multi-turn history), yet plain admission prefills every prompt into
freshly allocated pages.  Because the ragged fused step already reads
arbitrary pool pages through per-slot page tables (docs/serving.md
"Ragged fused step"), a cached prefix needs ZERO kernel changes: it is
just page-table entries pointing at pages another request filled.

**Radix index.**  A node is one FULL page of ``page_size`` token ids,
keyed by content + parent: each node's children are a dict keyed on the
child's raw token-id chunk bytes, so the path root -> node spells out a
token prefix page by page and lookup is a longest-prefix walk.  Every
node owns exactly one pool page (moved into the allocator's ``shared``
ledger at registration) whose KV holds those positions.

**COW ownership rule.**  A slot only ever WRITES pages it exclusively
owns.  Shared nodes are created only from *completed, immutable* full
pages — at harvest time, once ``pos`` has advanced past the page's last
position, the engine registers it here and the slot's remaining writes
land at positions ``>= pos``, i.e. strictly later pages.  The boundary
partial page is always private.  Decoding past a shared prefix is
therefore copy-on-write by construction: new tokens go to the slot's own
tail pages while shared pages are only read.

**Hits.**  ``acquire(prompt)`` walks the prompt's full-page chunks,
takes a reader reference on every matched page, and returns the pages to
splice into the new slot's table.  The match is capped so at least one
prompt token always prefills (the last prompt position must produce the
first logits).  Admission then reserves pages ONLY for the uncached tail
and the engine starts the prefill run at the first uncached token — the
positions are per-slot traced vectors, so no retrace.

**Eviction.**  LRU over refcount-0 nodes, leaf-first (references are
taken path-wise from the root, so a refcount-0 node's whole subtree is
refcount-0 and evicting leaves first keeps the tree consistent).  The
evictor is installed as the allocator's ``reclaimer``: under pool
pressure, cache-held pages are reclaimed BEFORE admission backpressures,
and never while referenced (``BlockAllocator.reclaim`` refuses
refcount > 0).

**Disaggregation.**  The index is per replica and transport-agnostic:
under prefill/decode disaggregation (serving/disagg.py) prefill replicas
keep their own caches — prefix-locality routing sends sibling prompts to
the replica that already holds their prefix — and a hand-off copies a
request's matched SHARED pages into private destination pages (the
reader reference pins them for the copy's duration; the source drops it
at release).  The decode side re-registers completed pages into its own
index at harvest, so transferred siblings dedup storage there too.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .paged_cache import BlockAllocator

__all__ = ["PrefixCache"]


class _PrefixNode:
    """One full page of cached KV: ``key`` is the raw bytes of the
    page's ``page_size`` token ids (the child key in ``parent.children``),
    ``page`` the pool page holding their KV."""

    __slots__ = ("parent", "key", "page", "children", "lru")

    def __init__(self, parent, key: bytes, page: int):
        self.parent = parent
        self.key = key
        self.page = page
        self.children: dict = {}
        self.lru = 0


def _chunk_key(tokens) -> bytes:
    return np.ascontiguousarray(np.asarray(tokens, np.int64)).tobytes()


class PrefixCache:
    """Radix index over completed KV pages, backed by ``allocator``'s
    shared-page ledger.  Host-side only — the device never sees it; all
    sharing happens through page-table entries."""

    def __init__(self, allocator: BlockAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._root = _PrefixNode(None, b"", -1)
        self._all: set = set()           # every live node (root excluded)
        self._clock = 0                  # monotonic LRU stamp
        self.stats = {"hits": 0, "partial_hits": 0, "misses": 0,
                      "evictions": 0, "cached_tokens": 0,
                      "inserted": 0, "deduped": 0}
        # eviction-before-backpressure: the allocator consults this when
        # the free list cannot cover a reservation
        allocator.reclaimer = self.evict

    # -- introspection -------------------------------------------------------
    @property
    def nodes(self) -> int:
        return len(self._all)

    @property
    def pages(self) -> int:
        """Pool pages the cache holds (== allocator.shared_pages when this
        is the only sharer)."""
        return len(self._all)

    def _cacheable_chunks(self, n_tokens: int) -> int:
        """Full-page chunks of an ``n_tokens`` prompt eligible for
        matching: capped below the last token so at least one position
        always prefills (its logits seed generation)."""
        return max(int(n_tokens) - 1, 0) // self.page_size

    # -- lookup --------------------------------------------------------------
    def match_len(self, prompt) -> int:
        """Longest cached prefix of ``prompt`` in tokens — read-only (no
        references taken).  The placement layer's locality signal."""
        prompt = np.asarray(prompt)
        ps, node, n = self.page_size, self._root, 0
        for i in range(self._cacheable_chunks(prompt.size)):
            child = node.children.get(_chunk_key(prompt[i * ps:(i + 1) * ps]))
            if child is None:
                break
            node, n = child, n + ps
        return n

    def acquire(self, prompt) -> Tuple[List[_PrefixNode], List[int], int]:
        """Longest cached prefix of ``prompt`` with a reader reference
        taken on every matched page.  Returns ``(nodes, pages,
        n_cached_tokens)`` — the caller splices ``pages`` into the slot's
        table, seats the slot at position ``n_cached_tokens``, and must
        ``release(nodes)`` at retirement (or immediately, if admission
        backpressures)."""
        prompt = np.asarray(prompt)
        ps, node = self.page_size, self._root
        nodes: List[_PrefixNode] = []
        for i in range(self._cacheable_chunks(prompt.size)):
            child = node.children.get(_chunk_key(prompt[i * ps:(i + 1) * ps]))
            if child is None:
                break
            nodes.append(child)
            node = child
        self._clock += 1
        for nd in nodes:
            self.allocator.ref(nd.page)
            nd.lru = self._clock
        return nodes, [nd.page for nd in nodes], len(nodes) * ps

    def release(self, nodes: Sequence[_PrefixNode]):
        """Drop the reader references ``acquire``/``extend`` took.  Pages
        stay cache-held (evictable) at refcount 0 — they return to the
        free list only through LRU eviction or ``flush``."""
        for nd in nodes:
            self.allocator.unref(nd.page)

    # -- registration --------------------------------------------------------
    def extend(self, parent: Optional[_PrefixNode], chunk,
               page: int) -> Tuple[_PrefixNode, bool]:
        """Register one completed full page under ``parent`` (None for the
        root).  ``chunk`` is the page's ``page_size`` token ids, ``page``
        the slot's exclusively-owned pool page holding their KV.

        New chunk: the page moves into the allocator's shared ledger
        (refcount 1 = the registering slot) and ``(node, True)`` is
        returned.  Duplicate chunk (another slot registered identical
        content first): the EXISTING node gains a reference and ``(node,
        False)`` is returned — the caller adopts the existing shared page
        and frees its private duplicate, so identical prefixes dedup to
        one physical copy."""
        node = self._root if parent is None else parent
        key = _chunk_key(chunk)
        if len(key) != 8 * self.page_size:
            raise ValueError(
                f"extend: chunk has {len(key) // 8} tokens, want a full "
                f"page of {self.page_size} (partial pages stay private)")
        self._clock += 1
        child = node.children.get(key)
        if child is not None:
            self.allocator.ref(child.page)
            child.lru = self._clock
            self.stats["deduped"] += 1
            return child, False
        self.allocator.share(page)
        child = _PrefixNode(node, key, page)
        child.lru = self._clock
        node.children[key] = child
        self._all.add(child)
        self.stats["inserted"] += 1
        return child, True

    # -- eviction ------------------------------------------------------------
    def evict(self, n: int) -> int:
        """Reclaim up to ``n`` pages from refcount-0 nodes, LRU-first and
        leaf-first.  Installed as the allocator's ``reclaimer`` so pool
        pressure drains the cache before admission backpressures.
        Returns the number of pages actually reclaimed."""
        import heapq

        rc = self.allocator.refcount
        heap = [(nd.lru, nd.page, nd) for nd in self._all
                if not nd.children and rc(nd.page) == 0]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < n:
            _, _, nd = heapq.heappop(heap)
            self.allocator.reclaim(nd.page)
            parent = nd.parent
            del parent.children[nd.key]
            self._all.discard(nd)
            freed += 1
            if (parent is not self._root and not parent.children
                    and rc(parent.page) == 0):
                heapq.heappush(heap, (parent.lru, parent.page, parent))
        self.stats["evictions"] += freed
        return freed

    def flush(self):
        """Drop the whole index and return every page to the free list —
        the rebuild path (docs/serving.md "Failure model"): a fresh pool's
        content is zeroed, so cached KV is invalid.  All references must
        already be released (every seated slot was failed and retired
        before ``_rebuild`` runs); a live reference here is a bug."""
        for nd in self._all:
            rc = self.allocator.refcount(nd.page)
            if rc:
                raise RuntimeError(
                    f"flush: page {nd.page} still has {rc} reader(s) — "
                    "flush must only run after every slot retired")
        for nd in self._all:
            self.allocator.reclaim(nd.page)
        self.stats["evictions"] += len(self._all)
        self._all.clear()
        self._root.children.clear()
