"""Observers: collect activation/weight statistics during calibration.

Reference: python/paddle/quantization/observers/abs_max.py
(AbsmaxObserver -> AbsmaxObserverLayer) and base_observer.py.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor


class BaseObserver(Layer):
    """Observes tensors flowing through and accumulates a quant scale
    (reference base_observer.py BaseObserver: a Layer whose forward is
    identity + statistics)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = quant_bits
        self._scale = None

    def forward(self, x: Tensor) -> Tensor:
        self._observe(x)
        return x

    def _observe(self, x: Tensor):
        raise NotImplementedError

    def bit_length(self) -> int:
        return self._quant_bits

    def quant_axis(self):
        return -1

    def scales(self):
        """The calibrated scale (max abs / qmax)."""
        if self._scale is None:
            return None
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def zero_points(self):
        return Tensor(jnp.zeros((), jnp.float32))

    def _instance(self, layer):
        """Factory protocol: a QuantConfig entry is a TEMPLATE — every
        matched layer gets its own observer so per-layer calibration
        statistics never cross-contaminate (reference
        quantization/factory.py ObserverFactory._get_class)."""
        return type(self)(quant_bits=self._quant_bits)


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (reference observers/abs_max.py)."""

    def _observe(self, x: Tensor):
        m = float(jnp.max(jnp.abs(x._value)))
        self._scale = m if self._scale is None else max(self._scale, m)


class AVGObserver(BaseObserver):
    """Average of per-batch max |x| (reference observers/avg.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._sum = 0.0
        self._n = 0

    def _observe(self, x: Tensor):
        self._sum += float(jnp.max(jnp.abs(x._value)))
        self._n += 1
        self._scale = self._sum / self._n
