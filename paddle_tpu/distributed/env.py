"""Distributed environment.

Reference: python/paddle/distributed/parallel.py (init_parallel_env:913,
ParallelEnv) driven by PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS env vars
set by the launcher.

TPU-native model: a single controller process drives all local chips via
SPMD (jit + shardings over a Mesh); multi-host jobs run one controller per
host coordinated by jax.distributed. "rank"/"world_size" therefore mean the
*process* rank (host) for host-level logic (data loading, logging) while
device-level parallelism is expressed through mesh axes — the analog of the
reference's process-per-GPU model collapsing into process-per-host.
"""
from __future__ import annotations

import os
from typing import Optional

import jax


def _env_int(name, default=0):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ParallelEnv:
    def __init__(self):
        self.rank = _env_int("PADDLE_TRAINER_ID", _env_int("RANK", 0))
        self.world_size = _env_int("PADDLE_TRAINERS_NUM", _env_int("WORLD_SIZE", 1))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = eps.split(",") if eps else []
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.device_id = _env_int("FLAGS_selected_tpus", 0)
        self.nrings = 1

    @property
    def local_rank(self):
        return self.rank

    @property
    def dev_id(self):
        return self.device_id


_parallel_env: Optional[ParallelEnv] = None
_initialized = False
_store = None  # rank-0-hosted native TCPStore (kept for p2p/barriers)


def _env() -> ParallelEnv:
    global _parallel_env
    if _parallel_env is None:
        _parallel_env = ParallelEnv()
    return _parallel_env


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return _env().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return _env().world_size


def is_initialized() -> bool:
    return _initialized


def get_store():
    """The job's rendezvous TCPStore (reference: tcp_store.h:120, created
    by init_parallel_env).  None on single-process jobs."""
    return _store


def init_parallel_env():
    """Bring up the multi-host runtime (reference parallel.py:913). On a
    single host this is a no-op beyond recording the env; on pods it
    rendezvouses through the native TCPStore and calls
    jax.distributed.initialize using the launcher-provided coordinator."""
    global _initialized, _store
    env = _env()
    if _initialized:
        return env
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ENDPOINT")
    if env.world_size > 1 and coord:
        # rendezvous barrier through the native TCPStore (reference
        # tcp_store.h:120): rank 0 hosts; all ranks sync before the XLA
        # coordinator handshake so slow-starting ranks don't time out.
        # The store is KEPT (get_store) — cross-host send/recv and
        # barriers ride it after bring-up.
        try:
            from ..core.native.tcp_store import TCPStore

            host, port = coord.split(":")[0], int(coord.split(":")[1])
            store = TCPStore(host=host, port=port + 1,
                             is_master=(env.rank == 0), world_size=env.world_size)
            if store._local is None:  # real socket store only — the
                # in-process fallback cannot synchronize separate ranks
                # (sweep=False: the satisfied-barrier sentinel must stay so
                # an elastic-RESTARTED rank re-running bring-up passes
                # instantly instead of re-arming a fresh counter and
                # hanging — docs/distributed_faults.md)
                store.barrier("init_parallel_env", env.world_size,
                              sweep=False)
                _store = store
        except Exception:
            pass  # rendezvous is best-effort; jax.distributed retries anyway
        if not os.environ.get("PADDLE_TPU_NO_JAX_DIST"):
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=env.world_size,
                    process_id=env.rank,
                )
            except Exception as e:  # already initialized or local testing
                if "already" not in str(e).lower():
                    import warnings

                    warnings.warn(f"jax.distributed.initialize failed: {e}")
    _initialized = True
    return env
