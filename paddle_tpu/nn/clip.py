"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm/Norm/Value consumed by optimizers)."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from ..ops import dispatch

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, Tensor(g._value * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip. Under hybrid parallel the HybridParallelOptimizer
    swaps in a group-aware variant that sums squared norms across mesh axes
    (reference dygraph_optimizer/hybrid_parallel_optimizer.py:238)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm

    def _global_norm_sq(self, params_grads):
        sq = None
        for _, g in params_grads:
            if g is None:
                continue
            v = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            sq = v if sq is None else sq + v
        return sq

    def __call__(self, params_grads):
        sq = self._global_norm_sq(params_grads)
        if sq is None:
            return params_grads
        gn = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value.astype(jnp.float32) * scale).astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros([], jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(p.grad._value)) for p in params]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(p.grad._value), norm_type)) for p in params),
            1.0 / norm_type,
        )
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad._set_value(p.grad._value * scale)
    return Tensor(total)
