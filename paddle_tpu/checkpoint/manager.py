"""Crash-consistent checkpoint directory manager.

Reference: python/paddle/distributed/auto_parallel/static/dist_saver.py
(DistributedSaver) pairs with fleet elastic's restart contract — the
recovery half of fault tolerance.  TPU-native design: a checkpoint is a
*directory* committed with one atomic ``os.rename``; everything inside it
(payload pickles + ``manifest.json`` with per-file SHA-256 digests) is
written and fsynced in a hidden temp dir first, so a crash at ANY point —
mid-payload, pre-manifest, pre-rename — leaves either the complete
checkpoint or garbage that ``latest()`` provably skips.  Serialization and
disk I/O run on a background writer thread (at most one in flight), so the
train step pays only the host snapshot.

Layout (see docs/checkpointing.md):

    <dir>/
      ckpt-00000042/            committed checkpoint (atomic rename target)
        state.pkl               pickled host snapshot (chunked writes)
        manifest.json           step/epoch, format version, file digests
      .tmp-ckpt-00000043-...    in-flight staging dir (never selected)
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..telemetry import trace as _ttrace

__all__ = ["CheckpointManager", "CheckpointError", "CheckpointInfo"]

MANIFEST_NAME = "manifest.json"
PAYLOAD_NAME = "state.pkl"
FORMAT_VERSION = 1
_CKPT_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"
_WRITE_CHUNK = 1 << 20  # 1 MiB payload chunks (crash-injection granularity)


class CheckpointError(RuntimeError):
    """Raised for writer failures (re-raised on the next save()/wait())
    and for restore() of a corrupt checkpoint."""


class CheckpointInfo:
    """A validated, committed checkpoint."""

    __slots__ = ("path", "step", "epoch", "manifest")

    def __init__(self, path: str, manifest: Dict[str, Any]):
        self.path = path
        self.step = int(manifest.get("step", -1))
        self.epoch = int(manifest.get("epoch", 0))
        self.manifest = manifest

    def __repr__(self):
        return f"CheckpointInfo(step={self.step}, path={self.path!r})"


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointManager:
    def __init__(self, directory: str, keep_last_k: int = 3,
                 async_save: bool = True):
        self._dir = os.path.abspath(directory)
        self._keep = max(int(keep_last_k), 1)
        self._async = async_save
        self._inflight: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        self._lock = threading.Lock()
        # test-only fault injection: fn(point_name) may raise to simulate a
        # crash at that point of the write pipeline (see tools/crash_gate.py)
        self._fault_hook: Optional[Callable[[str], None]] = None
        # positive-validation cache: committed dirs are immutable, so a
        # checkpoint that validated once need not be re-read and re-hashed
        # by every subsequent latest()/GC pass (keyed on manifest/payload
        # mtimes + size so external corruption that rewrites a file is
        # still caught; restore() always re-verifies the digest)
        self._valid_cache: Dict[str, tuple] = {}
        os.makedirs(self._dir, exist_ok=True)
        self._clean_stale_tmp()

    # -- properties ------------------------------------------------------
    @property
    def directory(self) -> str:
        return self._dir

    # -- save ------------------------------------------------------------
    def save(self, tree: Any, step: int, epoch: int = 0,
             meta: Optional[Dict[str, Any]] = None,
             blocking: Optional[bool] = None):
        """Snapshot ``tree`` (nested dict/list of numpy leaves — use
        TrainState.capture or checkpoint.to_host) and commit it as
        checkpoint ``step``.  With async_save the caller only pays the
        in-memory snapshot; serialization + fsync + rename happen on the
        writer thread.  A previous writer failure is re-raised here."""
        blocking = (not self._async) if blocking is None else blocking
        # at most one in-flight write: drain the previous one first (disk
        # slower than the save cadence degrades to blocking, never to an
        # unbounded queue of host snapshots)
        self.wait()
        if blocking:
            self._write(tree, int(step), int(epoch), dict(meta or {}))
            return
        t = threading.Thread(
            target=self._write_guarded,
            args=(tree, int(step), int(epoch), dict(meta or {})),
            name=f"ckpt-writer-{step}", daemon=True)
        self._inflight = t
        t.start()

    def wait(self):
        """Block until the in-flight write (if any) commits; re-raise its
        error as CheckpointError."""
        t = self._inflight
        if t is not None:
            t.join()
            self._inflight = None
        with self._lock:
            err, self._writer_error = self._writer_error, None
        if err is not None:
            raise CheckpointError(
                f"async checkpoint writer failed: {err!r}") from err

    close = wait

    def _write_guarded(self, tree, step, epoch, meta):
        try:
            self._write(tree, step, epoch, meta)
        except BaseException as e:  # noqa: BLE001 — surfaced on next save()
            with self._lock:
                self._writer_error = e

    def _hook(self, point: str):
        if self._fault_hook is not None:
            self._fault_hook(point)

    def _write(self, tree, step: int, epoch: int, meta: Dict[str, Any]):
        tmp = os.path.join(
            self._dir,
            f"{_TMP_PREFIX}{_CKPT_PREFIX}{step:08d}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        try:
            # the span records on the CALLING thread — for async saves
            # that is the ckpt-writer thread, so the exported trace shows
            # the serialize/fsync/commit pipeline on its own row,
            # interleaved with (not blocking) the train-step spans
            with _ttrace.span("ckpt.write", step=step):
                self._write_staged(tree, step, epoch, meta, tmp)
        except BaseException:
            # a FAILED (not crashed) write must not leak its staging dir —
            # transient ENOSPC/EIO on a long-lived trainer would otherwise
            # accumulate full-payload tmp dirs on an already-full disk
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def _write_staged(self, tree, step: int, epoch: int,
                      meta: Dict[str, Any], tmp: str):
        final = os.path.join(self._dir, f"{_CKPT_PREFIX}{step:08d}")
        os.makedirs(tmp)
        self._hook("after_tmpdir")
        with _ttrace.span("ckpt.serialize"):
            payload = pickle.dumps(tree, protocol=4)
        ppath = os.path.join(tmp, PAYLOAD_NAME)
        with _ttrace.span("ckpt.payload", bytes=len(payload)):
            with open(ppath, "wb") as f:
                for off in range(0, len(payload), _WRITE_CHUNK):
                    f.write(payload[off:off + _WRITE_CHUNK])
                    self._hook("mid_payload")
                f.flush()
                os.fsync(f.fileno())
        self._hook("after_payload")
        manifest = {
            "format_version": FORMAT_VERSION,
            "framework_version": _framework_version(),
            "step": step,
            "epoch": epoch,
            "meta": meta,
            "files": {PAYLOAD_NAME: {"sha256": _sha256_bytes(payload),
                                     "size": len(payload)}},
        }
        self._hook("before_manifest")
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        self._hook("before_commit")
        with _ttrace.span("ckpt.commit"):
            if os.path.exists(final):
                # re-save of the same step: displace the old dir, commit,
                # then drop the old content.  The brief both-absent window
                # is covered by the previous checkpoint (latest() falls
                # back).
                stale = final + f".gc-{uuid.uuid4().hex[:8]}"
                os.rename(final, stale)
                os.rename(tmp, final)
                shutil.rmtree(stale, ignore_errors=True)
            else:
                os.rename(tmp, final)
            _fsync_dir(self._dir)
            self._gc()

    # -- discovery / validation -----------------------------------------
    @staticmethod
    def _step_of(name: str) -> int:
        # order by the PARSED step, not the name: lexicographic order
        # inverts once a step outgrows the 8-digit zero-pad
        try:
            return int(name[len(_CKPT_PREFIX):])
        except ValueError:
            return -1

    def _committed_dirs(self) -> List[str]:
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return []
        out = [n for n in names
               if n.startswith(_CKPT_PREFIX) and ".gc-" not in n]
        # newest step first
        return sorted(out, key=self._step_of, reverse=True)

    def _cache_key(self, path: str, files: Dict[str, Any]):
        try:
            key = [os.stat(os.path.join(path, MANIFEST_NAME)).st_mtime_ns]
            for fname in files:
                st = os.stat(os.path.join(path, fname))
                key += [st.st_mtime_ns, st.st_size]
            return tuple(key)
        except OSError:
            return None

    def _validate(self, name: str) -> Optional[CheckpointInfo]:
        path = os.path.join(self._dir, name)
        mpath = os.path.join(path, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            self._valid_cache.pop(name, None)
            return None
        if manifest.get("format_version") != FORMAT_VERSION:
            return None
        files = manifest.get("files")
        if not isinstance(files, dict) or not files:
            return None
        key = self._cache_key(path, files)
        cached = self._valid_cache.get(name)
        if cached is not None and key is not None and cached[0] == key:
            return cached[1]
        for fname, rec in files.items():
            fpath = os.path.join(path, fname)
            try:
                if os.path.getsize(fpath) != rec["size"]:
                    return None
                with open(fpath, "rb") as f:
                    if _sha256_bytes(f.read()) != rec["sha256"]:
                        return None
            except (OSError, KeyError, TypeError):
                return None
        info = CheckpointInfo(path, manifest)
        if key is not None:
            self._valid_cache[name] = (key, info)
        return info

    def checkpoints(self) -> List[CheckpointInfo]:
        """All VALID committed checkpoints, newest step first.  Truncated,
        partial, and corrupt directories are silently skipped."""
        out = []
        for name in self._committed_dirs():
            info = self._validate(name)
            if info is not None:
                out.append(info)
        return out

    def latest(self) -> Optional[CheckpointInfo]:
        """Newest checkpoint that passes full manifest + digest
        validation; None when no valid checkpoint exists."""
        for name in self._committed_dirs():
            info = self._validate(name)
            if info is not None:
                return info
        return None

    # -- restore ---------------------------------------------------------
    def restore(self, info: Optional[CheckpointInfo] = None):
        """Load a checkpoint's payload tree.  Defaults to latest().
        Returns (tree, manifest) or raises CheckpointError when nothing
        valid exists (or the given checkpoint fails validation)."""
        if info is None:
            info = self.latest()
            if info is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self._dir}")
        ppath = os.path.join(info.path, PAYLOAD_NAME)
        rec = info.manifest["files"][PAYLOAD_NAME]
        try:
            with open(ppath, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise CheckpointError(f"unreadable checkpoint payload: {e}") from e
        if len(payload) != rec["size"] or _sha256_bytes(payload) != rec["sha256"]:
            raise CheckpointError(
                f"checkpoint payload digest mismatch in {info.path} "
                "(corrupted after commit)")
        try:
            tree = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001
            raise CheckpointError(
                f"checkpoint payload unpickle failed in {info.path}: {e!r}") from e
        return tree, info.manifest

    # -- retention -------------------------------------------------------
    def prune_newer_than(self, step: int):
        """Drop every committed checkpoint with step > ``step``.

        Elastic rollback support (docs/distributed_faults.md): after the
        members agree to resume from ``step``, any newer checkpoint on
        disk belongs to the ABANDONED timeline — leaving it would make a
        later ``latest()`` (or a later recovery's resume exchange) offer
        state the new timeline never produced."""
        self.wait()
        for name in self._committed_dirs():
            if self._step_of(name) > int(step):
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)
                self._valid_cache.pop(name, None)

    def _gc(self):
        """Keep the newest ``keep_last_k`` VALID checkpoints; drop older
        valid ones and any invalid committed garbage.  keep>=1 means the
        newest valid checkpoint is never deleted — and garbage is only
        collected when at least one valid checkpoint exists."""
        valid, invalid = [], []
        for name in self._committed_dirs():
            (valid if self._validate(name) is not None else invalid).append(name)
        if not valid:
            return
        # .gc- dirs are displaced old content of a re-saved step; a crash
        # between the two commit renames can orphan one
        stale_gc = [n for n in os.listdir(self._dir)
                    if n.startswith(_CKPT_PREFIX) and ".gc-" in n]
        for name in valid[self._keep:] + invalid + stale_gc:
            shutil.rmtree(os.path.join(self._dir, name), ignore_errors=True)
            self._valid_cache.pop(name, None)

    def _clean_stale_tmp(self):
        """Remove staging dirs left by crashed writers of PREVIOUS
        processes (ours are tracked by the in-flight thread)."""
        pid = str(os.getpid())
        for name in os.listdir(self._dir):
            if not name.startswith(_TMP_PREFIX):
                continue
            parts = name.split("-")
            if len(parts) >= 2 and parts[-2] == pid:
                continue
            shutil.rmtree(os.path.join(self._dir, name), ignore_errors=True)


def _framework_version() -> str:
    try:
        from ..version import __version__
        return str(__version__)
    except Exception:  # noqa: BLE001
        return "unknown"
