"""Benchmark: GPT train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The headline metric is tokens/sec/chip on the flagship GPT train step
(fwd + bwd + AdamW fused into a single XLA program via jit.to_static),
with MFU derived from the Megatron FLOPs formula. vs_baseline compares
MFU against the 45% north-star target (BASELINE.json: "GPT-3 1.3B
hybrid-parallel trains at >=45% MFU ... zero CUDA deps").

Resilience (round-1 postmortem, BENCH_r01 rc=1 / MULTICHIP_r01 rc=124):
the TPU backend (axon PJRT plugin) can fail OR hang — at init or later at
compile time — so no in-process defense suffices.  Structure:

  parent: probe backend init in a throwaway subprocess (cheap to kill),
          then run the measured workload in a watchdog-timed child; on
          any failure/timeout fall back to a clean-env CPU child; ALWAYS
          print exactly one JSON line.
  child (--child): the actual benchmark.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
_CPU_GUARD = "_PADDLE_TPU_BENCH_CPU_CHILD"

# bf16 matmuls for the MXU: the bench path uses AMP O1 (reference
# amp_guard list-based casting), so keep default matmul precision.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "default")
# persistent compilation cache: repeated bench runs skip recompiles
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")


def _emit(metric, value, unit, vs_baseline):
    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }))
    sys.stdout.flush()


def _peak_flops_per_chip(device_kind: str) -> float:
    """bf16 peak FLOP/s by TPU generation (public spec sheet numbers).

    device_kind strings vary ('TPU v5', 'TPU v5 lite', 'TPU v5p', ...);
    'lite' marks the e-class parts, bare v5 is v5p-class."""
    gen = (os.environ.get("PALLAS_AXON_TPU_GEN", "") or "").lower()
    kind = (device_kind or "").lower()
    for probe in (gen, kind):
        if not probe:
            continue
        if "v6" in probe:
            return 918e12
        if "v5e" in probe or ("v5" in probe and "lite" in probe):
            return 197e12
        if "v5" in probe:
            return 459e12
        if "v4" in probe:
            return 275e12
        if "v3" in probe:
            return 123e12
        if "v2" in probe:
            return 45e12
    return 197e12  # conservative default (v5e class)


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------

def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] + [_REPO_ROOT]
    )
    env[_CPU_GUARD] = "1"
    return env


def _probe_backend(timeout=240.0) -> bool:
    """Backend-init probe in a throwaway subprocess.  Init can hang (not
    just raise), so this must be out-of-process and killable."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout)
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stderr.write(f"bench: backend ok: {proc.stdout.strip()}\n")
            return True
        sys.stderr.write(f"bench: backend probe rc={proc.returncode}: "
                         f"{(proc.stderr or '').strip()[-500:]}\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench: backend probe timed out after {timeout}s\n")
    return False


def _run_child(env, timeout):
    """Run the measured workload in a watchdog-timed child; return its JSON
    line or None.  A backend that initializes but hangs at compile/execute
    is killed by the timeout instead of wedging the whole bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, cwd=_REPO_ROOT, timeout=timeout,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"bench: child timed out after {timeout}s\n")
        return None
    sys.stderr.write((proc.stderr or "")[-2000:])
    if proc.returncode != 0:
        sys.stderr.write(f"bench: child rc={proc.returncode}\n")
        return None
    for line in (proc.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            return line
    sys.stderr.write("bench: child produced no JSON line\n")
    return None


def parent():
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "1500"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "900"))
    line = None
    if _probe_backend():
        line = _run_child(dict(os.environ), tpu_timeout)
    if line is None:
        sys.stderr.write("bench: falling back to clean-env CPU child\n")
        line = _run_child(_cpu_env(), cpu_timeout)
    if line is None:
        _emit("gpt_small_train_tokens_per_sec_per_chip", 0.0,
              "tokens/s (bench failed on both tpu and cpu paths)", 0.0)
        return
    print(line)
    sys.stdout.flush()


# ---------------------------------------------------------------------------
# child: the actual benchmark
# ---------------------------------------------------------------------------

def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_small,
    )

    devs = jax.devices()
    on_tpu = devs[0].platform != "cpu"
    # CPU fallback uses a toy shape so the bench always completes
    if on_tpu:
        batch, seq = 8, 1024
        cfg = gpt_small(hidden_dropout=0.0, attention_dropout=0.0)
        steps = 10
    else:
        batch, seq = 2, 128
        cfg = gpt_small(hidden_dropout=0.0, attention_dropout=0.0)
        cfg.num_layers = 2
        steps = 3

    pt.seed(0)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")

    @pt.jit.to_static
    def train_step(ids, labels):
        with pt.amp.auto_cast(enable=True, level="O1", dtype="bfloat16"):
            loss = crit(model(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # warmup (eager) + scout/compile + 1 compiled call
    for _ in range(3):
        loss = train_step(ids, labels)
    float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = train_step(ids, labels)
    final = float(loss)  # forces completion of the async chain
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"bench diverged: loss={final}"

    tokens_per_sec = batch * seq * steps / dt

    # Megatron-LM FLOPs/iteration: 72 b s L h^2 (1 + s/(6h) + V/(12 L h))
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    flops_per_iter = 72 * batch * seq * L * h * h * (1 + seq / (6 * h) + V / (12 * L * h))
    model_flops_per_sec = flops_per_iter * steps / dt
    peak = _peak_flops_per_chip(getattr(devs[0], "device_kind", ""))
    mfu = model_flops_per_sec / peak

    _emit(
        "gpt_small_train_tokens_per_sec_per_chip",
        round(tokens_per_sec, 1),
        f"tokens/s (bs={batch} seq={seq} mfu={mfu:.3f} on {'tpu' if on_tpu else 'cpu'})",
        round(mfu / 0.45, 4),
    )


if __name__ == "__main__":
    if "--child" in sys.argv:
        main()
    else:
        parent()
