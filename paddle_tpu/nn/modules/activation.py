"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer


def _act_layer(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # positional args map onto the functional's extra params in order
            fn = getattr(F, fname)
            import inspect

            params = [
                p for p in inspect.signature(fn).parameters if p not in ("x", "name")
            ]
            for p, a in zip(params, args):
                self._kwargs[p] = a
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)

    _Act.__name__ = fname.title().replace("_", "")
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
GELU = _act_layer("gelu")
SiLU = _act_layer("silu")
Swish = _act_layer("swish")
Mish = _act_layer("mish")
Sigmoid = _act_layer("sigmoid")
Tanh = _act_layer("tanh")
LogSigmoid = _act_layer("log_sigmoid")
Softsign = _act_layer("softsign")
Tanhshrink = _act_layer("tanhshrink")
LeakyReLU = _act_layer("leaky_relu")
ELU = _act_layer("elu")
SELU = _act_layer("selu")
CELU = _act_layer("celu")
Hardtanh = _act_layer("hardtanh")
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Hardshrink = _act_layer("hardshrink")
Softshrink = _act_layer("softshrink")
Softplus = _act_layer("softplus")
ThresholdedReLU = _act_layer("thresholded_relu")
Maxout = _act_layer("maxout")
GLU = _act_layer("glu")
RReLU = _act_layer("rrelu")


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from ..initializer import Constant

        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)
