"""Eager op compilation cache (core/op_cache.py + ops/dispatch.py).

Covers the ISSUE-1 tentpole: shape-keyed hit/miss behavior, LRU bound,
cached-vs-uncached numeric parity (tolerance 0) on a representative op set,
the jit.to_static tracing fallback, stats plumbing, and a two-thread
dispatch smoke test.
"""
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import op_cache


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Each test sees an empty cache/stats and the default flags."""
    pt.set_flags({"FLAGS_eager_op_cache": True})
    op_cache.clear(reset=True)
    yield
    pt.set_flags({"FLAGS_eager_op_cache": True,
                  "FLAGS_eager_op_cache_size": 1024})
    op_cache.clear(reset=True)


def _t(arr, requires_grad=False):
    t = pt.to_tensor(np.asarray(arr))
    t.stop_gradient = not requires_grad
    return t


# ---------------------------------------------------------------------------
# hit / miss keying
# ---------------------------------------------------------------------------

def test_repeat_same_shape_hits():
    x = _t(np.random.randn(8, 8).astype(np.float32))
    y = _t(np.random.randn(8, 8).astype(np.float32))
    for _ in range(5):
        pt.matmul(x, y)
    st = op_cache.stats()["matmul"]
    assert st["calls"] == 5
    assert st["misses"] == 1 and st["traces"] == 1
    assert st["hits"] == 4
    assert st["fallbacks"] == {}


def test_shape_change_misses():
    for n in (4, 8, 16):
        x = _t(np.random.randn(n, n).astype(np.float32))
        pt.matmul(x, x)
    st = op_cache.stats()["matmul"]
    assert st["misses"] == 3 and st["hits"] == 0


def test_dtype_change_misses():
    a32 = _t(np.random.randn(8).astype(np.float32))
    a64 = _t(np.random.randn(8).astype(np.float64))
    pt.tanh(a32)
    pt.tanh(a64)
    st = op_cache.stats()["tanh"]
    assert st["misses"] == 2 and st["hits"] == 0


def test_attr_change_misses():
    x = _t(np.random.randn(4, 6).astype(np.float32))
    pt.sum(x, axis=0)
    pt.sum(x, axis=1)
    pt.sum(x, axis=1)          # hit
    pt.sum(x, axis=1, keepdim=True)
    st = op_cache.stats()["sum"]
    assert st["misses"] == 3 and st["hits"] == 1


def test_grad_bit_separates_entries():
    xn = _t(np.random.randn(4, 4).astype(np.float32))
    xg = _t(np.random.randn(4, 4).astype(np.float32), requires_grad=True)
    pt.tanh(xn)                # fwd-mode entry
    pt.tanh(xg)                # vjp-mode entry: same avals, different mode
    st = op_cache.stats()["tanh"]
    assert st["misses"] == 2 and st["hits"] == 0


def test_scalar_type_does_not_collide():
    # True == 1 == 1.0 under Python equality; the key must still separate
    # them or the first caller's constant (and dtype) gets baked in
    t = _t(np.array([1, 0], np.int64))
    out_bool = t + True
    out_int = t + 1
    out_float = t + 1.0
    pt.set_flags({"FLAGS_eager_op_cache": False})
    ref_bool = t + True
    ref_int = t + 1
    ref_float = t + 1.0
    for got, want in ((out_bool, ref_bool), (out_int, ref_int),
                      (out_float, ref_float)):
        assert np.asarray(got._value).dtype == np.asarray(want._value).dtype
        np.testing.assert_array_equal(np.asarray(got._value),
                                      np.asarray(want._value))


def test_churn_guard_bounds_per_call_tracing():
    # an op that only ever misses (fresh scalar every call) must stop
    # paying a jit trace per call after the guard trips
    x = _t(np.random.randn(4).astype(np.float32))
    for i in range(100):
        x + float(i + 0.5)
    st = op_cache.stats()["add"]
    assert st["fallbacks"].get("churn", 0) > 0
    assert st["traces"] < 75  # guard capped entry builds (100 without it)
    # values stay correct through the fallback
    out = x + 1234.5
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(x._value) + 1234.5, rtol=0)


def test_churn_guard_not_masked_by_tensor_tensor_hits():
    # the guard is scoped per (fn, mode, avals) FAMILY: hits on the
    # tensor-tensor form of an op must not keep scalar churn compiling
    x = _t(np.random.randn(4).astype(np.float32))
    u = _t(np.random.randn(4).astype(np.float32))
    for i in range(100):
        x * u                      # same op name, hitting family
        x * (0.1 + i * 1e-4)       # varying scalar: churning family
    st = op_cache.stats()["multiply"]
    assert st["hits"] >= 99        # tensor-tensor path keeps hitting
    assert st["fallbacks"].get("churn", 0) > 0
    assert st["traces"] < 80       # 1 tensor-tensor + throttled scalars
    # a previously-cached scalar value still hits (lookup precedes guard)
    op_cache.reset_stats()
    x * 0.1
    assert op_cache.stats()["multiply"]["hits"] == 1


def test_jit_error_entry_discarded_not_poisoned():
    from paddle_tpu.ops import dispatch

    calls = {"n": 0}

    def flaky(a):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient")
        return a * 2.0

    op_cache.mark_stable(flaky)
    x = _t(np.random.randn(4).astype(np.float32))
    # first dispatch: the jit trace hits the transient error, the eager
    # fallback re-runs flaky (which now succeeds) — no exception escapes
    out = dispatch.apply(flaky, x, op_name="flaky")
    np.testing.assert_array_equal(np.asarray(out._value),
                                  np.asarray(x._value) * 2.0)
    st = op_cache.stats()["flaky"]
    assert st["fallbacks"].get("jit_error") == 1
    # the failed entry was dropped (not poisoned): the next call builds a
    # fresh one, and the call after that hits it
    dispatch.apply(flaky, x, op_name="flaky")
    out2 = dispatch.apply(flaky, x, op_name="flaky")
    np.testing.assert_array_equal(np.asarray(out2._value),
                                  np.asarray(x._value) * 2.0)
    st = op_cache.stats()["flaky"]
    assert st["hits"] == 1
    assert "unjittable" not in st["fallbacks"]


def test_scalar_operand_is_part_of_key():
    x = _t(np.random.randn(8).astype(np.float32))
    a = (x + 2.0)._value
    b = (x + 3.0)._value
    c = (x + 2.0)._value
    st = op_cache.stats()["add"]
    assert st["misses"] == 2 and st["hits"] == 1
    np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    assert not np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# LRU bound
# ---------------------------------------------------------------------------

def test_lru_eviction_respects_flag_bound():
    pt.set_flags({"FLAGS_eager_op_cache_size": 4})
    for n in range(1, 9):  # 8 distinct shape keys
        x = _t(np.random.randn(n).astype(np.float32))
        pt.tanh(x)
    info = op_cache.cache_info()
    assert info["entries"] <= 4
    assert info["capacity"] == 4
    # re-dispatching the most recent shape still hits
    x = _t(np.random.randn(8).astype(np.float32))
    pt.tanh(x)
    assert op_cache.stats()["tanh"]["hits"] == 1


# ---------------------------------------------------------------------------
# numeric parity: cached vs uncached, tolerance 0
# ---------------------------------------------------------------------------

def _fwd_bwd(fn, arrays, cached):
    pt.set_flags({"FLAGS_eager_op_cache": cached})
    ts = [_t(a, requires_grad=True) for a in arrays]
    out = fn(*ts)
    pt.autograd.backward(
        out, pt.to_tensor(np.ones(out.shape, dtype=np.asarray(
            out._value).dtype)))
    return (np.asarray(out._value),
            [np.asarray(t.grad._value) for t in ts])


REPRESENTATIVE_OPS = [
    ("unary", lambda x: pt.tanh(x),
     [np.random.RandomState(0).randn(6, 5).astype(np.float32)]),
    ("binary_broadcast", lambda x, y: pt.add(x, y),
     [np.random.RandomState(1).randn(4, 5).astype(np.float32),
      np.random.RandomState(2).randn(5).astype(np.float32)]),
    ("matmul", lambda x, y: pt.matmul(x, y),
     [np.random.RandomState(3).randn(4, 6).astype(np.float32),
      np.random.RandomState(4).randn(6, 3).astype(np.float32)]),
    ("reduction_attrs", lambda x: pt.sum(x, axis=1, keepdim=True),
     [np.random.RandomState(5).randn(4, 6).astype(np.float32)]),
]


@pytest.mark.parametrize("label,fn,arrays", REPRESENTATIVE_OPS,
                         ids=[r[0] for r in REPRESENTATIVE_OPS])
def test_cached_grad_parity_exact(label, fn, arrays):
    out_u, grads_u = _fwd_bwd(fn, arrays, cached=False)
    out_c, grads_c = _fwd_bwd(fn, arrays, cached=True)
    out_c2, grads_c2 = _fwd_bwd(fn, arrays, cached=True)  # via cache hit
    np.testing.assert_array_equal(out_u, out_c)
    np.testing.assert_array_equal(out_u, out_c2)
    for gu, gc, gc2 in zip(grads_u, grads_c, grads_c2):
        np.testing.assert_array_equal(gu, gc)
        np.testing.assert_array_equal(gu, gc2)


def test_cached_backward_is_jitted():
    x = _t(np.random.randn(4, 4).astype(np.float32), requires_grad=True)
    y = pt.tanh(x)
    pt.autograd.backward(y, pt.to_tensor(np.ones((4, 4), np.float32)))
    st = op_cache.stats()["tanh"]
    assert st["bwd_calls"] == 1 and st["bwd_jitted"] == 1


def test_retain_graph_double_backward():
    x = _t(np.random.randn(3).astype(np.float32), requires_grad=True)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    g1 = np.asarray(x.grad._value).copy()
    x.grad = None
    y.backward()
    np.testing.assert_array_equal(g1, np.asarray(x.grad._value))


def test_higher_order_grad_unaffected():
    x = _t(np.array([2.0], np.float32), requires_grad=True)
    y = (x * x * x).sum()
    (gx,) = pt.autograd.grad(y, x, create_graph=True)
    (ggx,) = pt.autograd.grad(gx.sum(), x)
    np.testing.assert_allclose(np.asarray(ggx._value), [12.0], rtol=1e-6)


# ---------------------------------------------------------------------------
# fallbacks
# ---------------------------------------------------------------------------

def test_no_caching_under_to_static():
    def fn(a, b):
        return pt.matmul(a, b) + 1.0

    compiled = pt.jit.to_static(fn)
    x = _t(np.random.randn(4, 4).astype(np.float32))
    y = _t(np.random.randn(4, 4).astype(np.float32))
    before = op_cache.cache_info()["entries"]
    out = compiled(x, y)
    assert np.isfinite(np.asarray(out._value)).all()
    assert op_cache.cache_info()["entries"] == before  # tracers never cached
    summ = op_cache.summary()
    fb = summ["fallbacks"]
    assert fb.get("tracing", 0) + fb.get("tracer_input", 0) > 0
    assert summ["hits"] == 0 and summ["misses"] == 0


def test_flag_disable_falls_back():
    pt.set_flags({"FLAGS_eager_op_cache": False})
    x = _t(np.random.randn(4).astype(np.float32))
    pt.tanh(x)
    st = op_cache.stats()["tanh"]
    assert st["fallbacks"].get("disabled") == 1
    assert op_cache.cache_info()["entries"] == 0


def test_unstable_fn_falls_back():
    from paddle_tpu.ops import dispatch

    x = _t(np.random.randn(4).astype(np.float32))
    out = dispatch.apply(lambda a: a * 2.0, x, op_name="doubler")
    np.testing.assert_array_equal(np.asarray(out._value),
                                  np.asarray(x._value) * 2.0)
    assert op_cache.stats()["doubler"]["fallbacks"].get("unstable_fn") == 1


def test_unhashable_attr_falls_back():
    from paddle_tpu.ops import dispatch

    def scaled(a, *, w):
        return a * w

    op_cache.mark_stable(scaled)
    x = _t(np.random.randn(4).astype(np.float32))
    out = dispatch.apply(scaled, x, op_name="scaled",
                         w=np.ones(4, np.float32))  # ndarray: unhashable
    assert np.isfinite(np.asarray(out._value)).all()
    assert op_cache.stats()["scaled"]["fallbacks"].get("unhashable") == 1


# ---------------------------------------------------------------------------
# stats plumbing
# ---------------------------------------------------------------------------

def test_stats_and_reset():
    x = _t(np.random.randn(4).astype(np.float32))
    pt.tanh(x)
    pt.tanh(x)
    st = op_cache.stats()
    assert st["tanh"]["calls"] == 2
    summ = op_cache.summary()
    assert summ["calls"] >= 2 and 0.0 <= summ["hit_rate"] <= 1.0
    op_cache.reset_stats()
    assert op_cache.stats() == {}
    # entries survive a stats reset; hits keep accruing from zero
    pt.tanh(x)
    assert op_cache.stats()["tanh"]["hits"] == 1


def test_log_stats_writes_summary():
    import io

    x = _t(np.random.randn(4).astype(np.float32))
    pt.tanh(x)
    buf = io.StringIO()
    op_cache.log_stats(stream=buf)
    text = buf.getvalue()
    assert "eager op-cache" in text and "tanh" in text


# ---------------------------------------------------------------------------
# thread-safety smoke
# ---------------------------------------------------------------------------

def test_two_thread_dispatch_smoke():
    x = _t(np.random.randn(8, 8).astype(np.float32))
    y = _t(np.random.randn(8, 8).astype(np.float32))
    errs = []

    def worker():
        try:
            for _ in range(100):
                z = pt.add(pt.matmul(x, y), 1.0)
            assert np.isfinite(np.asarray(z._value)).all()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    st = op_cache.stats()
    assert st["matmul"]["calls"] == 200
    assert st["add"]["calls"] == 200
    # after the first trace everything hits (no lost updates under the lock)
    assert st["matmul"]["hits"] == 199 and st["matmul"]["misses"] == 1
