"""Eager op compilation cache — jitted eager dispatch.

TPU-native analog of the reference's cached kernel dispatch: eager mode in
the reference never re-resolves a kernel per call — ``matmul_ad_func`` looks
up a phi KernelFactory entry keyed by KernelKey once and the generated
GradNode reuses compiled kernels (SURVEY.md §3.1).  Our dispatch layer used
to do the opposite: every differentiated op call re-traced a fresh
``jax.vjp``, paying full Python+tracing overhead per op — the dominant cost
of eager mode off the ``jit.to_static`` path.

This module is the KernelFactory analog.  Dispatch asks :func:`acquire` for
a compiled entry keyed by

    (raw_fn identity, mode, input avals (shape/dtype/weak_type),
     hashable attrs, AMP state)

where ``mode`` is ``"fwd"`` (no-grad path: a plain ``jax.jit`` of the
forward) or ``"vjp"`` (grad path: a jitted ``jax.vjp`` returning outputs
plus the residual ``Partial`` — a pytree, so it round-trips through jit).
The grad path's backward then runs through one shared jitted runner
(:data:`_vjp_runner`), so repeated eager calls hit JAX's C++ dispatch fast
path in BOTH directions instead of re-tracing.

Fallback rules (all transparent — the un-jitted path is always correct):

- ``tracing``       under a ``jit.to_static`` trace (tracers must never be
                    cached: an entry would leak the trace).
- ``tracer_input``  a raw input is a jax tracer (any foreign transform).
- ``disabled``      ``FLAGS_eager_op_cache`` is off.
- ``opt_out``       the caller passed ``_cacheable=False`` (e.g. the
                    autograd engine's per-node ``create_graph`` closures).
- ``unstable_fn``   raw_fn is a per-call closure/lambda — caching it would
                    trace on every call (identity never repeats).
- ``unhashable``    an attr can't participate in a dict key.
- ``unjittable``    the op's first jitted run raised a concretization
                    error (host-value-dependent Python inside raw_fn); the
                    entry is poisoned so later calls skip jit immediately.
- ``jit_error``     the jitted run raised a non-concretization error
                    (transient runtime failure or a genuine op error); the
                    entry is dropped so a later call can retry, and the
                    eager re-run surfaces any genuine error naturally.
- ``churn``         one (raw_fn, mode, avals) family keeps minting fresh
                    attr keys (64+ misses — e.g. a per-step-varying Python
                    scalar); only every 16th miss still builds an entry.
                    Cached attr values for the family keep hitting.

The cache is a bounded LRU (``FLAGS_eager_op_cache_size``) guarded by one
lock; per-op dispatch counters (calls / hits / misses / traces / backward
dispatches / fallback reasons) are exposed via :func:`stats`,
:func:`reset_stats` and :func:`summary`, dumped at exit when
``FLAGS_eager_cache_log`` is set, and surfaced by bench.py next to
tokens/s.  See docs/eager_dispatch.md.
"""
from __future__ import annotations

import atexit
import functools
import json
import sys
import threading
import types
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from . import flags as _flags

__all__ = [
    "acquire", "mark_stable", "fn_stable", "CachedVJP", "count_bwd",
    "fail_entry", "wrap_tuple_fn", "stats", "reset_stats", "summary",
    "cache_info", "clear", "log_stats",
]

_lock = threading.RLock()
_cache: "OrderedDict[Tuple, _Entry]" = OrderedDict()
_stats: Dict[str, Dict[str, Any]] = {}

# per-op distinct input-aval signatures, for the graph linter's GL007
# retrace-churn pass (and users): how many distinct shape keys each op was
# dispatched under, visible WITHOUT enabling any logging.  Bounded per op —
# past the cap the count saturates (the churn verdict is long since in),
# and the op lands in _shape_key_overflow so stats() can say EXPLICITLY
# that its count is a lower bound (GL007 must never under-report churn
# silently).
_SHAPE_KEY_CAP = 512
_shape_keys: Dict[str, set] = {}
_shape_key_overflow: set = set()


class _Entry:
    """One compiled dispatch artifact.  ``fn`` is the jitted callable
    (``None`` marks a poisoned, known-unjittable key); ``multi`` records
    whether the op's raw output was a tuple (set during the first trace of
    a vjp-mode entry by the tuple_fn side channel); ``bwd`` is the entry's
    own jitted VJP runner (vjp mode only) so evicting the entry also frees
    its compiled backward executables; ``key`` back-references the cache
    slot for discard-on-failure."""

    __slots__ = ("fn", "multi", "bwd", "key")

    def __init__(self, fn):
        self.fn = fn
        self.multi = None
        self.bwd = None
        self.key = None


# Concretization-class errors mean raw_fn is VALID eager code that can
# never be jitted (host-value-dependent branching, data-dependent output
# shapes) — those keys are poisoned permanently.  Anything else (transient
# runtime failures, genuine op errors) just discards the entry so a later
# call can retry; a genuine error re-raises from the eager fallback.
_POISON_ERRORS = tuple(
    e for e in (getattr(jax.errors, n, None)
                for n in ("ConcretizationTypeError",
                          "NonConcreteBooleanIndexError"))
    if e is not None)


def fail_entry(entry: "_Entry", op_name: str, exc: BaseException):
    """A jitted call for ``entry`` raised: poison unjittable keys, drop the
    entry for everything else (the caller re-runs the eager path)."""
    if isinstance(exc, _POISON_ERRORS):
        entry.fn = None
        _count_fallback(op_name, "unjittable")
        return
    _count_fallback(op_name, "jit_error")
    with _lock:
        if _cache.get(entry.key) is entry:
            del _cache[entry.key]


def _op_stats(name: str) -> Dict[str, Any]:
    st = _stats.get(name)
    if st is None:
        st = _stats[name] = {
            "calls": 0, "hits": 0, "misses": 0, "traces": 0,
            "bwd_calls": 0, "bwd_jitted": 0, "fallbacks": {},
        }
    return st


def _count_fallback(name: str, reason: str):
    with _lock:
        fb = _op_stats(name)["fallbacks"]
        fb[reason] = fb.get(reason, 0) + 1


# ---------------------------------------------------------------------------
# cacheability of the raw function
# ---------------------------------------------------------------------------

def mark_stable(fn: Callable, stable: bool = True) -> Callable:
    """Declare that ``fn`` has a stable identity across calls (op factories
    call this once per op definition on their closure helpers)."""
    try:
        fn._pt_cache_stable = stable
    except (AttributeError, TypeError):
        pass  # ufuncs / C callables: the heuristic already accepts them
    return fn


def fn_stable(fn: Callable) -> bool:
    """True when caching on ``fn``'s identity can ever hit: module-level
    functions and callable singletons (jnp ufuncs, PjitFunctions) qualify;
    lambdas, per-call nested defs, partials and bound methods do not —
    keying on those would jit-trace every single call."""
    explicit = getattr(fn, "_pt_cache_stable", None)
    if explicit is not None:
        return bool(explicit)
    if isinstance(fn, (functools.partial, types.MethodType)):
        return False
    if isinstance(fn, types.FunctionType):
        return (fn.__name__ != "<lambda>"
                and "<locals>" not in getattr(fn, "__qualname__", ""))
    return callable(fn)


# ---------------------------------------------------------------------------
# key construction
# ---------------------------------------------------------------------------

def _aval_key(r):
    aval = getattr(r, "aval", None)
    if aval is not None:
        return (tuple(aval.shape), aval.dtype, getattr(aval, "weak_type", False))
    return (tuple(np.shape(r)), np.result_type(r), False)


def _make_key(raw_fn, mode, raws, attrs, extra_key):
    avals = tuple(_aval_key(r) for r in raws)
    # attr values carry their TYPE: Python equality would otherwise collide
    # True == 1 == 1.0 onto one compiled entry with the first caller's
    # constant (and dtype) baked in
    attrs_key = tuple(sorted(((k, type(v), v) for k, v in attrs.items()),
                             key=lambda item: item[0])) if attrs else ()
    key = (raw_fn, mode, avals, attrs_key, extra_key)
    hash(key)  # TypeError for unhashable attrs -> caller falls back
    return key


# ---------------------------------------------------------------------------
# the LRU + acquire
# ---------------------------------------------------------------------------

def wrap_tuple_fn(fwd, set_multi):
    """Normalize ``fwd`` to always return a tuple, reporting whether the
    raw output was one via ``set_multi`` (runs at trace time).  Shared by
    the cached entry builder and dispatch's un-jitted vjp fallback so the
    two grad paths can't drift."""
    def tuple_fn(*xs):
        o = fwd(*xs)
        if isinstance(o, tuple):
            set_multi(True)
            return o
        set_multi(False)
        return (o,)

    return tuple_fn


def _run_partial(p, cts):
    return p(cts)


def _build_entry(fwd, mode) -> _Entry:
    if mode != "vjp":
        return _Entry(jax.jit(fwd))

    entry = _Entry(None)
    tuple_fn = wrap_tuple_fn(
        fwd, lambda m: setattr(entry, "multi", m))
    entry.fn = jax.jit(lambda *xs: jax.vjp(tuple_fn, *xs))
    # per-entry backward runner: the residual Partial is a pytree argument,
    # so this jit compiles once per (residual, cotangent) avals and its
    # executables die WITH the entry (a shared module-level runner would
    # accumulate specializations past LRU eviction forever)
    entry.bwd = jax.jit(_run_partial)
    return entry


# churn guard state: distinct-key miss count per FAMILY — the key minus
# its attrs, i.e. (raw_fn, mode, avals, extra).  A family that mints a
# fresh attrs key on (nearly) every call — a per-step-varying Python
# scalar, say — would pay a jit trace per call, worse than the un-jitted
# path it replaced.  Scoping to the family (not the op name) keeps
# tensor-tensor hits on the same op from masking scalar churn.
_CHURN_MISSES = 64     # family misses before the guard engages
_CHURN_REPROBE = 16    # …after which only every Nth miss builds an entry
_family: Dict[Tuple, int] = {}


def acquire(op_name: str, raw_fn: Callable, fwd: Callable, raws, attrs,
            mode: str, extra_key=None, tracing: bool = False,
            opted_out: bool = False) -> Optional[_Entry]:
    """KernelFactory lookup for one dispatch: return a compiled entry for
    this (op, shapes, attrs, mode) or ``None`` when the call must take the
    un-jitted path (counting the fallback reason either way).

    ``extra_key`` may be a callable (evaluated lazily, only when the call
    is actually cacheable).  One lock acquisition per dispatch."""
    reason = None
    key = None
    if opted_out:
        reason = "opt_out"
    elif not _flags.flag("FLAGS_eager_op_cache"):
        reason = "disabled"
    elif tracing:
        reason = "tracing"
    elif not fn_stable(raw_fn):
        reason = "unstable_fn"
    elif any(isinstance(r, jax.core.Tracer) for r in raws):
        reason = "tracer_input"
    else:
        try:
            extra = extra_key() if callable(extra_key) else extra_key
            key = _make_key(raw_fn, mode, raws, attrs, extra)
        except TypeError:
            reason = "unhashable"

    with _lock:
        st = _op_stats(op_name)
        st["calls"] += 1
        if reason is not None:
            fb = st["fallbacks"]
            fb[reason] = fb.get(reason, 0) + 1
            return None
        sk = _shape_keys.setdefault(op_name, set())
        if len(sk) < _SHAPE_KEY_CAP:
            sk.add(key[2])  # the input avals slot of the cache key
        elif key[2] not in sk:
            # the capped set is saturated AND this is a genuinely new
            # signature: the count is now a lower bound — flag it
            _shape_key_overflow.add(op_name)
        entry = _cache.get(key)
        if entry is not None:
            _cache.move_to_end(key)
            if entry.fn is None:  # poisoned: known-unjittable op
                fb = st["fallbacks"]
                fb["unjittable"] = fb.get("unjittable", 0) + 1
                return None
            st["hits"] += 1
            return entry
        st["misses"] += 1
        famkey = (key[0], key[1], key[2], key[4])
        if len(_family) > 8192:  # heuristic state, safe to forget
            _family.clear()
        fam_misses = _family[famkey] = _family.get(famkey, 0) + 1
        if fam_misses > _CHURN_MISSES and fam_misses % _CHURN_REPROBE:
            # already-cached attr values for this family keep hitting
            # above; only the minting of NEW entries is throttled
            fb = st["fallbacks"]
            fb["churn"] = fb.get("churn", 0) + 1
            return None
        st["traces"] += 1  # first call of a fresh entry jit-traces
        entry = _build_entry(fwd, mode)
        entry.key = key
        _cache[key] = entry
        limit = int(_flags.flag("FLAGS_eager_op_cache_size"))
        while len(_cache) > max(1, limit):
            _cache.popitem(last=False)
        return entry


# ---------------------------------------------------------------------------
# cached backward execution
# ---------------------------------------------------------------------------

class CachedVJP:
    """GradNode backward callable for the cached grad path: holds the
    residual ``Partial`` produced by the jitted forward and executes it
    through its entry's jitted runner (repeated backward calls hit that
    jit's C++ cache; the runner is freed when the entry is evicted and
    every referencing GradNode is done)."""

    __slots__ = ("partial", "op_name", "bwd")

    def __init__(self, partial, op_name: str, bwd):
        self.partial = partial
        self.op_name = op_name
        self.bwd = bwd

    def __call__(self, cotangents):
        try:
            return self.bwd(self.partial, cotangents)
        except Exception:
            # never trade an answer for a cache: run the Partial directly
            # (a genuine error re-raises here with its natural traceback)
            _count_fallback(self.op_name, "unjittable")
            return self.partial(cotangents)


def count_bwd(op_name: str, jitted: bool):
    """Called by the autograd engine per backward node dispatch."""
    with _lock:
        st = _op_stats(op_name)
        st["bwd_calls"] += 1
        if jitted:
            st["bwd_jitted"] += 1


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def stats() -> Dict[str, Dict[str, Any]]:
    """Per-op dispatch counters (deep copy).  ``shape_keys`` is the number
    of distinct input-aval signatures the op was dispatched under (the
    GL007 retrace-churn signal); ``shape_keys_overflow`` is True when the
    capped tracking set saturated AND new signatures kept arriving — the
    count is then a LOWER bound, and GL007 treats the op as churning
    regardless of any threshold."""
    with _lock:
        return {
            name: {**st, "fallbacks": dict(st["fallbacks"]),
                   "shape_keys": len(_shape_keys.get(name, ())),
                   "shape_keys_overflow": name in _shape_key_overflow}
            for name, st in _stats.items()
        }


def reset_stats():
    with _lock:
        _stats.clear()
        _shape_keys.clear()
        _shape_key_overflow.clear()


def summary() -> Dict[str, Any]:
    """Aggregate counters + hit rate, the bench.py one-liner payload."""
    with _lock:
        agg = {"ops": len(_stats), "calls": 0, "hits": 0, "misses": 0,
               "traces": 0, "bwd_calls": 0, "bwd_jitted": 0}
        fb: Dict[str, int] = {}
        for st in _stats.values():
            for k in ("calls", "hits", "misses", "traces", "bwd_calls",
                      "bwd_jitted"):
                agg[k] += st[k]
            for reason, n in st["fallbacks"].items():
                fb[reason] = fb.get(reason, 0) + n
        agg["fallbacks"] = fb
        looked_up = agg["hits"] + agg["misses"]
        agg["hit_rate"] = agg["hits"] / looked_up if looked_up else 0.0
        agg["entries"] = len(_cache)
        agg["capacity"] = int(_flags.flag("FLAGS_eager_op_cache_size"))
        return agg


def cache_info() -> Dict[str, int]:
    with _lock:
        return {"entries": len(_cache),
                "capacity": int(_flags.flag("FLAGS_eager_op_cache_size"))}


def clear(reset: bool = False):
    """Drop every compiled entry (and optionally the counters)."""
    with _lock:
        _cache.clear()
        _family.clear()
        if reset:
            _stats.clear()
            _shape_keys.clear()
            _shape_key_overflow.clear()


def log_stats(stream=None, top: int = 20):
    """FLAGS_eager_cache_log dump hook: aggregate line + hottest ops."""
    stream = stream if stream is not None else sys.stderr
    stream.write("[paddle_tpu] eager op-cache: " + json.dumps(summary()) + "\n")
    per_op = sorted(stats().items(), key=lambda kv: -kv[1]["calls"])[:top]
    for name, st in per_op:
        stream.write(
            f"[paddle_tpu]   {name}: calls={st['calls']} hits={st['hits']} "
            f"misses={st['misses']} traces={st['traces']} "
            f"bwd={st['bwd_calls']} fallbacks={st['fallbacks']}\n")


def _exit_dump():
    try:
        if _flags.flag("FLAGS_eager_cache_log"):
            log_stats()
    except Exception:
        pass


atexit.register(_exit_dump)
