"""Custom-device plugin boundary (N35; reference phi/capi +
device_manager.h registry): the registry is a real, mockable seam."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.device import (get_all_custom_device_type,
                               is_compiled_with_custom_device, plugin)


class FakeNPU(plugin.DeviceBackend):
    name = "fake_npu"

    def __init__(self):
        self.synced = 0

    def device_count(self):
        return 4

    def synchronize(self, device_id=0):
        self.synced += 1

    def memory_stats(self, device_id=0):
        return {"bytes_in_use": 123, "bytes_limit": 1000}


@pytest.fixture
def fake_backend():
    b = FakeNPU()
    plugin.register_backend(b)
    yield b
    plugin.unregister_backend("fake_npu")


def test_default_pjrt_backends_present():
    types = plugin.registered_types()
    assert "cpu" in types
    assert plugin.device_count("cpu") >= 1
    plugin.synchronize("cpu")  # must not raise
    assert isinstance(plugin.memory_stats("cpu"), dict)


def test_register_and_query_custom_backend(fake_backend):
    assert "fake_npu" in get_all_custom_device_type()
    assert is_compiled_with_custom_device("fake_npu")
    assert plugin.device_count("fake_npu") == 4
    plugin.synchronize("fake_npu", 1)
    assert fake_backend.synced == 1
    assert plugin.memory_stats("fake_npu")["bytes_limit"] == 1000


def test_duplicate_and_unknown_backends(fake_backend):
    with pytest.raises(ValueError, match="taken"):
        plugin.register_backend(FakeNPU())
    with pytest.raises(KeyError, match="no device backend"):
        plugin.get_backend("never_registered")
