"""Discrete distributions (reference: python/paddle/distribution/
bernoulli.py, categorical.py, geometric.py, multinomial.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..ops import dispatch
from ..ops.random import default_generator
from ..tensor import Tensor
from .continuous import _key_op
from .distribution import Distribution

__all__ = ["Bernoulli", "Categorical", "Geometric", "Multinomial"]

_EPS = 1e-7


def _clip_probs(p):
    return ops.clip(p, min=_EPS, max=1.0 - _EPS)


class Bernoulli(Distribution):
    """reference bernoulli.py Bernoulli(probs)."""

    def __init__(self, probs, name=None):
        self.probs = self._to_tensor(probs)[0]
        self.logits = ops.log(_clip_probs(self.probs)) - ops.log1p(-_clip_probs(self.probs))
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        full = self._extend_shape(shape)

        def fn(key, p):
            return jax.random.bernoulli(key, p, full).astype(p.dtype)

        out = _key_op(fn, self.probs, op_name="bernoulli_sample")
        out.stop_gradient = True
        return out

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (reference bernoulli.py rsample)."""
        full = self._extend_shape(shape)

        def fn(key, logits):
            u = jax.random.uniform(key, full, logits.dtype, minval=_EPS,
                                   maxval=1.0 - _EPS)
            l_noise = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((logits + l_noise) / temperature)

        return _key_op(fn, self.logits, op_name="bernoulli_rsample")

    def log_prob(self, value):
        value = self._to_tensor(value)[0]
        p = _clip_probs(self.probs)
        return value * ops.log(p) + (1.0 - value) * ops.log1p(-p)

    def entropy(self):
        p = _clip_probs(self.probs)
        return -(p * ops.log(p) + (1.0 - p) * ops.log1p(-p))

    def cdf(self, value):
        value = self._to_tensor(value)[0]
        zero = ops.zeros_like(self.probs)
        one = ops.ones_like(self.probs)
        mid = 1.0 - self.probs
        return ops.where(value < 0.0, zero, ops.where(value < 1.0, mid, one))

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Categorical(Distribution):
    """reference categorical.py Categorical(logits) — NB the reference takes
    UNNORMALIZED category scores; probabilities = softmax."""

    def __init__(self, logits, name=None):
        self.logits = self._to_tensor(logits)[0]
        super().__init__(tuple(self.logits.shape[:-1]))
        self._n = self.logits.shape[-1]

    @property
    def probs_tensor(self):
        from ..nn import functional as F

        return F.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        full = tuple(shape) + self._batch_shape

        def fn(key, logits):
            return jax.random.categorical(key, logits, axis=-1, shape=full or None)

        out = _key_op(fn, self.logits, op_name="categorical_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        from ..nn import functional as F

        value = self._to_tensor(value)[0]
        logp = F.log_softmax(self.logits, axis=-1)
        idx = ops.cast(value, "int64")
        # broadcast the categories table against the value batch
        if tuple(idx.shape) != tuple(logp.shape[:-1]):
            logp = ops.broadcast_to(logp, list(idx.shape) + [self._n])
        return ops.squeeze(ops.take_along_axis(logp, ops.unsqueeze(idx, -1), -1), -1)

    def probs(self, value):
        return ops.exp(self.log_prob(value))

    def entropy(self):
        from ..nn import functional as F

        logp = F.log_softmax(self.logits, axis=-1)
        return -ops.sum(ops.exp(logp) * logp, axis=-1)

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Geometric(Distribution):
    """reference geometric.py Geometric(probs): #failures before success,
    support {0, 1, 2, ...}."""

    def __init__(self, probs, name=None):
        self.probs = self._to_tensor(probs)[0]
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return (1.0 - self.probs) / self.probs

    @property
    def variance(self):
        return (1.0 - self.probs) / ops.square(self.probs)

    @property
    def stddev(self):
        return ops.sqrt(self.variance)

    def sample(self, shape=()):
        full = self._extend_shape(shape)

        def fn(key, p):
            u = jax.random.uniform(key, full, p.dtype, minval=_EPS,
                                   maxval=1.0 - _EPS)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        out = _key_op(fn, self.probs, op_name="geometric_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        value = self._to_tensor(value)[0]
        p = _clip_probs(self.probs)
        return value * ops.log1p(-p) + ops.log(p)

    def entropy(self):
        p = _clip_probs(self.probs)
        q = 1.0 - p
        return -(q * ops.log(q) + p * ops.log(p)) / p

    def cdf(self, value):
        value = self._to_tensor(value)[0]
        return 1.0 - ops.pow(1.0 - self.probs, value + 1.0)


class Multinomial(Distribution):
    """reference multinomial.py Multinomial(total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = self._to_tensor(probs)[0]
        shape = tuple(self.probs.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        full = tuple(shape) + self._batch_shape
        n = self.total_count

        def fn(key, p):
            logits = jnp.log(jnp.clip(p, _EPS))
            draws = jax.random.categorical(
                key, logits, axis=-1, shape=(n,) + full)
            onehot = jax.nn.one_hot(draws, p.shape[-1], dtype=p.dtype)
            return jnp.sum(onehot, axis=0)

        out = _key_op(fn, self.probs, op_name="multinomial_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        value = self._to_tensor(value)[0]
        logp = ops.log(_clip_probs(self.probs))
        return (ops.lgamma(ops.full_like(ops.sum(value, axis=-1), self.total_count + 1.0))
                - ops.sum(ops.lgamma(value + 1.0), axis=-1)
                + ops.sum(value * logp, axis=-1))

    def entropy(self):
        # no closed form; Monte-Carlo estimate would be dishonest — reference
        # computes via enumeration only for tiny supports, so raise like it
        # does for unsupported cases.
        raise NotImplementedError(
            "Multinomial.entropy has no closed form; estimate via samples")
