"""Global prefix cache: COW shared KV pages behind a radix index
(docs/serving.md "Prefix cache").

- ``pages_for_tokens``: THE ceil-division helper admission, speculative
  reservations, and tail-only reservation all share — boundary cases
  (prompt exactly at a page edge, max_new 0/1) pinned here;
- ``BlockAllocator`` shared-page ledger: share/ref/unref/reclaim
  lifecycle, double-free/over-release detection extended to refcounted
  release, the 4-term invariant ``free + used + spec + shared ==
  capacity``, and the pressure reclaimer hook (eviction BEFORE admission
  backpressure);
- the radix index itself: page-granular longest-prefix match, the
  last-page cap (at least one token always prefills), duplicate-chunk
  dedup/adoption, leaf-first LRU eviction that never touches a
  referenced node, flush refusing while pages are referenced;
- engine-level COW regression: with the cache ON, greedy output across
  interleaved shared-prefix arrivals is token-for-token identical to a
  prefix-cache-disabled engine (fp32 + bf16, layered + stacked) — the
  sharing peer's output is bitwise what an isolated run produces, which
  is exactly the copy-on-write guarantee;
- eviction under pool pressure, speculative-decoding composition,
  prefix-locality placement ranking, and the telemetry surface
  (counters/histogram exist even with the cache disabled).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import (
    GPTForPretraining, GPTStackedForPretraining, gpt_tiny,
)
from paddle_tpu.serving import (
    BlockAllocator,
    PrefixCache,
    PrefixLocalityPlacement,
    RequestState,
    ServingEngine,
    pages_for_tokens,
)
from paddle_tpu.telemetry import metrics as tm

N_NEW = 4


# ---------------------------------------------------------------------------
# pages_for_tokens: the ONE ceil-pages helper (admission, speculative
# reservations, tail-only reservation)
# ---------------------------------------------------------------------------

def test_pages_for_tokens_boundaries():
    assert pages_for_tokens(0, 16) == 0
    assert pages_for_tokens(1, 16) == 1
    assert pages_for_tokens(15, 16) == 1
    assert pages_for_tokens(16, 16) == 1        # exactly at the page edge
    assert pages_for_tokens(17, 16) == 2
    assert pages_for_tokens(32, 16) == 2
    # admission sizing: a prompt landing exactly on a page edge with
    # max_new 0 fits its pages; ONE more token rolls a fresh page
    prompt = 32
    assert pages_for_tokens(prompt + 0, 16) == 2
    assert pages_for_tokens(prompt + 1, 16) == 3


def test_pages_for_tokens_rejects_bad_inputs():
    with pytest.raises(ValueError, match="tokens"):
        pages_for_tokens(-1, 16)
    with pytest.raises(ValueError, match="page_size"):
        pages_for_tokens(4, 0)


# ---------------------------------------------------------------------------
# BlockAllocator shared-page ledger
# ---------------------------------------------------------------------------

def _ledger(a):
    return a.free_pages + a.used_pages + a.spec_pages + a.shared_pages


def test_share_ref_unref_reclaim_lifecycle():
    a = BlockAllocator(num_pages=6)             # capacity 5 (page 0 null)
    pages = a.alloc(2)
    assert _ledger(a) == a.capacity
    a.share(pages[0])                           # allocated -> shared @ 1
    assert a.shared_pages == 1 and a.used_pages == 1
    assert a.refcount(pages[0]) == 1
    assert _ledger(a) == a.capacity
    a.ref(pages[0])
    assert a.refcount(pages[0]) == 2
    a.unref(pages[0])
    a.unref(pages[0])
    assert a.refcount(pages[0]) == 0            # stays shared at 0
    assert a.shared_pages == 1 and _ledger(a) == a.capacity
    a.reclaim(pages[0])                         # refcount 0 -> free list
    assert a.shared_pages == 0 and a.free_pages == a.capacity - 1
    assert _ledger(a) == a.capacity
    a.free([pages[1]])
    assert a.free_pages == a.capacity


def test_shared_page_error_paths():
    a = BlockAllocator(num_pages=6)
    (p,) = a.alloc(1)
    with pytest.raises(ValueError, match="not currently allocated"):
        a.share(p + 1)                          # not allocated
    a.share(p)
    with pytest.raises(ValueError):
        a.share(p)                              # no longer exclusively owned
    with pytest.raises(ValueError, match="double free or foreign"):
        a.free([p])                             # shared pages aren't freed
    a.unref(p)
    with pytest.raises(ValueError, match="over-release"):
        a.unref(p)                              # over-release past zero
    with pytest.raises(ValueError, match="not shared"):
        a.ref(999)                              # never shared
    with pytest.raises(ValueError, match="not shared"):
        a.reclaim(999)
    a.ref(p)
    with pytest.raises(ValueError, match="reader"):
        a.reclaim(p)                            # still referenced
    a.unref(p)
    a.reclaim(p)
    assert a.free_pages == a.capacity and _ledger(a) == a.capacity


def test_reclaimer_hook_runs_before_shortage():
    """Pool pressure calls the reclaimer BEFORE declaring shortage: a
    zero-refcount shared page is reclaimed to satisfy the allocation;
    without the hook the same call backpressures (returns None)."""
    a = BlockAllocator(num_pages=4)             # capacity 3
    pages = a.alloc(3)
    for p in pages:
        a.share(p)
        a.unref(p)                              # 3 shared pages @ 0
    assert a.alloc(2) is None                   # no reclaimer installed
    reclaimed = []

    def reclaimer(n):
        # reclaim up to n still-cached pages (PrefixCache.evict's contract:
        # best effort over zero-refcount pages, never raises on shortfall)
        for p in pages:
            if len(reclaimed) >= len(pages) or n <= 0:
                break
            if a.refcount(p) == 0:
                a.reclaim(p)
                reclaimed.append(p)
                n -= 1

    a.reclaimer = reclaimer
    got = a.alloc(2)
    assert got is not None and len(got) == 2
    assert len(reclaimed) == 2
    assert _ledger(a) == a.capacity
    # reclaimer that cannot free enough still ends in clean backpressure
    assert a.alloc(5) is None
    assert _ledger(a) == a.capacity


# ---------------------------------------------------------------------------
# the radix index (pure host-side: no model, no engine)
# ---------------------------------------------------------------------------

PS = 4


def _register(cache, alloc, toks):
    """Register every full page of ``toks`` the way the engine does at
    page completion (extend with a fresh page, adopt on dedup), then
    release the registering slot's own references — the state after the
    registering request retires: cached at refcount 0."""
    nodes = []
    for i in range(len(toks) // PS):
        (page,) = alloc.alloc(1)
        node, owned = cache.extend(nodes[-1] if nodes else None,
                                   toks[i * PS:(i + 1) * PS], page)
        if not owned:
            alloc.free([page])
        nodes.append(node)
    cache.release(nodes)
    return nodes


def test_radix_longest_match_acquire_release():
    a = BlockAllocator(num_pages=12)
    c = PrefixCache(a, page_size=PS)
    toks = np.arange(12, dtype=np.int64)
    nodes = _register(c, a, toks)
    assert c.nodes == 3 and a.shared_pages == 3
    # longest-prefix walk, page-granular
    assert c.match_len(np.arange(13)) == 12
    assert c.match_len(np.concatenate([toks[:8], [99, 98]])) == 8
    assert c.match_len(np.array([7, 7, 7])) == 0
    got_nodes, got_pages, n = c.acquire(np.arange(13))
    assert n == 12 and [nd.page for nd in got_nodes] == got_pages
    assert all(a.refcount(p) == 1 for p in got_pages)
    c.release(got_nodes)
    assert all(a.refcount(p) == 0 for p in got_pages)
    assert _ledger(a) == a.capacity
    for nd in nodes:
        a.reclaim(nd.page)                      # cleanup path sanity


def test_acquire_always_leaves_one_token_to_prefill():
    """A prompt that is ENTIRELY cached would admit a slot with nothing
    to prefill; the match is capped so the last token always runs."""
    a = BlockAllocator(num_pages=12)
    c = PrefixCache(a, page_size=PS)
    toks = np.arange(8, dtype=np.int64)
    _register(c, a, toks)
    _, pages, n = c.acquire(toks)               # prompt == cached prefix
    assert n == PS and len(pages) == 1          # NOT 8: last page excluded
    assert c.match_len(toks) == PS


def test_radix_dedup_adopts_existing_node():
    a = BlockAllocator(num_pages=12)
    c = PrefixCache(a, page_size=PS)
    toks = np.arange(PS, dtype=np.int64)
    (n1,) = _register(c, a, toks)
    (p2,) = a.alloc(1)
    n2, owned = c.extend(None, toks, p2)
    assert n2 is n1 and owned is False          # duplicate chunk: adopt
    assert a.refcount(n1.page) == 1             # dedup bumped the ref
    assert c.nodes == 1 and c.stats["deduped"] == 1
    a.free([p2])                                # caller frees its duplicate
    a.unref(n1.page)
    assert _ledger(a) == a.capacity


def test_lru_eviction_leaf_first_never_referenced():
    a = BlockAllocator(num_pages=12)
    c = PrefixCache(a, page_size=PS)
    old = _register(c, a, np.arange(8, dtype=np.int64))
    new = _register(c, a, np.full(PS, 77, dtype=np.int64))
    held_nodes, _, _ = c.acquire(np.full(8, 77, dtype=np.int64))
    assert len(held_nodes) == 1                 # the 77-chunk, now @ 1
    freed = c.evict(10)                         # asks for more than exists
    # both nodes of the old chain go (leaf first unlinks the parent too);
    # the referenced node survives any demand
    assert freed == 2 and c.nodes == 1
    assert c.stats["evictions"] == 2
    assert new[0] in set(c._root.children.values())
    c.release(held_nodes)
    assert c.evict(10) == 1 and c.nodes == 0
    assert a.free_pages == a.capacity
    assert all(nd.page != 0 for nd in old)      # sanity: never the null page


def test_flush_refuses_while_referenced():
    a = BlockAllocator(num_pages=12)
    c = PrefixCache(a, page_size=PS)
    _register(c, a, np.arange(PS, dtype=np.int64))
    nodes, _, _ = c.acquire(np.arange(8, dtype=np.int64))
    with pytest.raises(RuntimeError, match="reader"):
        c.flush()
    c.release(nodes)
    c.flush()
    assert c.nodes == 0 and a.free_pages == a.capacity


# ---------------------------------------------------------------------------
# engine-level: COW parity, eviction under pressure, composition
# ---------------------------------------------------------------------------

def _models():
    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    return cfg


def _shared_prefix_prompts(cfg, rng, page_size=16):
    """Two system-prompt families x unique tails + one loner, interleaved
    so family siblings are in flight together (the COW window: a later
    sibling reads pages an earlier one wrote while both still decode)."""
    fam = [rng.randint(0, cfg.vocab_size, (20,)),
           rng.randint(0, cfg.vocab_size, (20,))]
    tails = [rng.randint(0, cfg.vocab_size, (k,)) for k in (3, 7, 5, 9)]
    return [
        np.concatenate([fam[0], tails[0]]),
        np.concatenate([fam[1], tails[1]]),
        np.concatenate([fam[0], tails[2]]),
        rng.randint(0, cfg.vocab_size, (11,)),
        np.concatenate([fam[1], tails[3]]),
        np.concatenate([fam[0], tails[1]]),
    ]


def _parity_combo(dtype, stacked):
    cfg = _models()
    model = (GPTStackedForPretraining(cfg) if stacked
             else GPTForPretraining(cfg))
    model.eval()
    rng = np.random.RandomState(5)
    prompts = _shared_prefix_prompts(cfg, rng)
    kw = dict(num_slots=2, page_size=16, max_context=64, cache_dtype=dtype)
    ref_eng = ServingEngine(model, **kw)
    refs = ref_eng.generate_batch(prompts, N_NEW)
    ref_eng.close()
    eng = ServingEngine(model, prefix_cache=True, **kw)
    # interleaved arrivals: 2 slots, 6 requests — siblings overlap
    reqs = [eng.submit(p, N_NEW) for p in prompts]
    eng.run_until_idle(max_steps=1000)
    for r, ref in zip(reqs, refs):
        assert r.state == RequestState.DONE
        assert np.array_equal(r.output_ids(), ref), (
            "prefix-cache engine diverged from the cache-disabled run")
    mets = eng.metrics()
    assert mets["prefix_hits"] + mets["prefix_partial_hits"] >= 1
    assert mets["prefix_cached_tokens"] >= 16
    a = eng.allocator
    assert a.used_pages == 0 and a.spec_pages == 0
    assert a.free_pages + a.shared_pages == a.capacity
    eng.close()


def test_cache_parity_fp32_layered():
    _parity_combo("float32", stacked=False)


def test_cache_parity_bf16_stacked():
    _parity_combo("bfloat16", stacked=True)


@pytest.mark.slow
def test_cache_parity_bf16_layered():
    _parity_combo("bfloat16", stacked=False)


@pytest.mark.slow
def test_cache_parity_fp32_stacked():
    _parity_combo("float32", stacked=True)


def test_eviction_under_pool_pressure_keeps_serving():
    """An admission that the free list alone cannot satisfy evicts LRU
    zero-refcount cache pages BEFORE backpressuring — and accounting
    stays exact through it."""
    cfg = _models()
    m = GPTForPretraining(cfg)
    m.eval()
    rng = np.random.RandomState(11)
    eng = ServingEngine(m, num_slots=1, page_size=16, max_context=48,
                        num_pages=7, cache_dtype="float32",
                        prefix_cache=True)
    a = eng.allocator
    for n_prompt, n_new in ((20, 4), (40, 6), (44, 2)):
        r = eng.submit(rng.randint(0, cfg.vocab_size, (n_prompt,)), n_new)
        eng.run_until_idle(max_steps=300)
        assert r.state == RequestState.DONE, (r.state, r.error)
        assert a.free_pages + a.used_pages + a.shared_pages == a.capacity
    assert a.free_pages == 1 and a.shared_pages == 5   # cache-full pool
    # 34 tokens -> 3 pages, 1 free: the reclaimer must evict 2 LRU pages
    r = eng.submit(rng.randint(0, cfg.vocab_size, (30,)), N_NEW)
    eng.run_until_idle(max_steps=300)
    assert r.state == RequestState.DONE, (r.state, r.error)
    mets = eng.metrics()
    assert mets["prefix_evictions"] >= 2
    assert a.used_pages == 0
    assert a.free_pages + a.shared_pages == a.capacity
    eng.close()


def test_speculative_engine_composes_with_prefix_cache():
    """Cached-prefix admission seeds the draft's catch-up backlog: greedy
    speculative output stays bit-identical and BOTH pools drain."""
    from paddle_tpu.serving import SpeculativeEngine

    cfg = _models()
    m = GPTForPretraining(cfg)
    m.eval()
    rng = np.random.RandomState(3)
    prompts = _shared_prefix_prompts(cfg, rng)[:4]
    kw = dict(num_slots=2, page_size=16, max_context=64,
              cache_dtype="float32")
    ref_eng = ServingEngine(m, **kw)
    refs = ref_eng.generate_batch(prompts, N_NEW)
    ref_eng.close()
    eng = SpeculativeEngine(m, m, spec_k=2, prefix_cache=True, **kw)
    reqs = [eng.submit(p, N_NEW) for p in prompts]
    eng.run_until_idle(max_steps=1000)
    for r, ref in zip(reqs, refs):
        assert r.state == RequestState.DONE, (r.state, r.error)
        assert np.array_equal(r.output_ids(), ref)
    mets = eng.metrics()
    assert mets["spec_acceptance_rate"] == 1.0      # same-model draft
    assert mets["prefix_hits"] + mets["prefix_partial_hits"] >= 1
    for alloc in (eng.allocator, eng.draft.allocator):
        assert alloc.used_pages == 0 and alloc.spec_pages == 0
    assert (eng.allocator.free_pages + eng.allocator.shared_pages
            == eng.allocator.capacity)
    eng.close()


# ---------------------------------------------------------------------------
# placement + telemetry surfaces
# ---------------------------------------------------------------------------

class _FakeQueue:
    def __init__(self, depth):
        self.depth = depth


class _FakeAlloc:
    capacity, used_pages = 10, 0


class _FakeSched:
    active_slots = 0


class _FakeReplica:
    def __init__(self, depth, match):
        self.queue = _FakeQueue(depth)
        self.allocator = _FakeAlloc()
        self.scheduler = _FakeSched()
        self.prefix_cache = None
        if match is not None:
            self.prefix_cache = type(
                "C", (), {"match_len": staticmethod(lambda p, m=match: m)})()


def test_prefix_locality_placement_ranking():
    """Longest cached prefix wins; load only breaks ties; replicas with
    no cache rank as match 0 (plain least-loaded among themselves)."""
    prompt = np.arange(32)
    pol = PrefixLocalityPlacement()
    engines = [_FakeReplica(0, 0), _FakeReplica(5, 32), _FakeReplica(0, 16)]
    assert pol.rank_for(engines, prompt) == [1, 2, 0]
    # ties on match fall back to least-loaded, then index
    engines = [_FakeReplica(3, 16), _FakeReplica(1, 16), _FakeReplica(1, None)]
    assert pol.rank_for(engines, prompt) == [1, 0, 2]
    # the base class rank() is untouched (load-only)
    assert pol.rank([_FakeReplica(2, None), _FakeReplica(0, None)]) == [1, 0]


def test_prefix_metrics_exist_with_cache_disabled():
    """metrics() keys and the Prometheus series exist whether or not the
    cache is on — dashboards and the sharded sum never KeyError."""
    cfg = _models()
    m = GPTForPretraining(cfg)
    m.eval()
    eng = ServingEngine(m, num_slots=1, page_size=16, max_context=32,
                        cache_dtype="float32")
    try:
        assert eng.prefix_cache is None
        mets = eng.metrics()
        for k in ("prefix_hits", "prefix_partial_hits", "prefix_misses",
                  "prefix_evictions", "prefix_cached_tokens",
                  "prefix_hit_rate", "cached_tokens_share",
                  "prefix_cache_pages", "prefix_cache_nodes",
                  "shared_pages"):
            assert mets[k] == 0 or mets[k] == 0.0, (k, mets[k])
        text = tm.registry().prometheus_text()
        assert "serving_prefix_hits_total" in text
        assert "serving_prefix_evictions_total" in text
        assert "serving_prefix_cached_tokens" in text
    finally:
        eng.close()
