"""Audio feature layers (reference: python/paddle/audio/features/layers.py —
Spectrogram:24, MelSpectrogram:106, LogMelSpectrogram:206, MFCC:309).

Each layer is a pure function of its input built from the framework's
stft + matmul ops, so feature extraction fuses into the surrounding
compiled program (the reference runs these as eager op chains).
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..nn.layer import Layer
from ..ops import dispatch
from ..tensor import Tensor
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length)

    def forward(self, x: Tensor) -> Tensor:
        from .. import signal

        stft_out = signal.stft(
            x, self.n_fft, hop_length=self.hop_length,
            win_length=self.win_length, window=self.fft_window,
            center=self.center, pad_mode=self.pad_mode)
        power = self.power

        def raw(c):
            return jnp.abs(c) ** power

        return dispatch.apply(raw, stft_out, op_name="spectrogram")


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm)

    def forward(self, x: Tensor) -> Tensor:
        spect = self._spectrogram(x)  # [..., freq, time]
        fb = self.fbank_matrix

        def raw(s, f):
            return jnp.einsum("mf,...ft->...mt", f, s)

        return dispatch.apply(raw, spect, fb, op_name="mel_spectrogram")


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x: Tensor) -> Tensor:
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db)
        self.dct_matrix = AF.create_dct(n_mfcc=n_mfcc, n_mels=n_mels)

    def forward(self, x: Tensor) -> Tensor:
        log_mel = self._log_melspectrogram(x)  # [..., n_mels, time]
        d = self.dct_matrix

        def raw(s, dm):
            return jnp.einsum("mk,...mt->...kt", dm, s)

        return dispatch.apply(raw, log_mel, d, op_name="mfcc")
