"""static: graph-capture compatibility surface.

The reference maintains a full static-graph stack (ProgramDesc + executors,
SURVEY.md §1-L3b). In the TPU-native design the compiled representation IS
the jitted XLA program produced by ``jit.to_static``; this namespace keeps
the user-facing entry points (InputSpec, save/load inference models) without
a separate graph IR.
"""
from . import nn  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .nn import Assert, cond, while_loop  # noqa: F401
from ..jit.save_load import load as load_inference_model_impl  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.save(layer, path, input_spec=...) — the jitted "
        "program is the inference model"
    )


def load_inference_model(path_prefix, executor=None, **kwargs):
    return load_inference_model_impl(path_prefix)
