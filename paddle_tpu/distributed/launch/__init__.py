"""Process launcher (reference: python/paddle/distributed/launch/ —
controllers/collective.py builds per-rank env and forks pods;
master rendezvous in controllers/master.py).

TPU-native: on TPU pods each HOST runs one process that owns all local
chips (SPMD single-controller), so the launcher's job is to start one
worker per host entry (or N local workers for CPU simulation), wire the
PADDLE_* env contract, stream logs, and propagate failures.
"""
from .main import launch_main  # noqa: F401
