"""Recurrent layers: SimpleRNN/LSTM/GRU cells + RNN/BiRNN wrappers.

Reference API: python/paddle/nn/layer/rnn.py (RNNCellBase:112, SimpleRNNCell
:251, LSTMCell:394, GRUCell:557, RNN:700, BiRNN:797, SimpleRNN:1035,
LSTM:1157, GRU:1291).  The reference runs a per-timestep python loop in
dygraph and a `_rnn_static_graph` while_loop in static mode; on TPU the whole
time dimension is one ``lax.scan`` dispatched as a single op, so eager
autograd captures ONE VJP for the layer and ``jit.to_static`` compiles the
recurrence into a single fused XLA while loop (no per-step dispatch).

Weight layout matches the reference cells: ``weight_ih [G*H, I]``,
``weight_hh [G*H, H]``, biases ``[G*H]`` with gate order i,f,g,o (LSTM —
reference rnn.py:490 chunks) and r,z,c (GRU — reference rnn.py:648).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ... import ops
from ...ops import dispatch
from ...tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer

__all__ = [
    "RNNCellBase",
    "SimpleRNNCell",
    "LSTMCell",
    "GRUCell",
    "RNN",
    "BiRNN",
    "SimpleRNN",
    "LSTM",
    "GRU",
]


def _ensure_tuple(states):
    return states if isinstance(states, (tuple, list)) else (states,)


class RNNCellBase(Layer):
    """Base: get_initial_states builds zero states shaped by state_shape
    (reference rnn.py:112)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        # state_shape may be one shape or a tuple of shapes (LSTM: (h, c))
        if isinstance(shape[0], (tuple, list)):
            return tuple(
                ops.full([batch] + list(s), init_value,
                         dtype=dtype or "float32")
                for s in shape
            )
        return ops.full([batch] + list(shape), init_value, dtype=dtype or "float32")


def _uniform_init(hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-std, std)


class _GateCell(RNNCellBase):
    """Shared parameter scaffold for the three cells."""

    def __init__(self, input_size, hidden_size, n_gates,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive, got "
                             f"{hidden_size}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _uniform_init(hidden_size)
        self.weight_ih = self.create_parameter(
            [n_gates * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [n_gates * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        if bias_ih_attr is not False:
            self.bias_ih = self.create_parameter(
                [n_gates * hidden_size], attr=bias_ih_attr, is_bias=True,
                default_initializer=init)
        else:
            self.bias_ih = None
        if bias_hh_attr is not False:
            self.bias_hh = self.create_parameter(
                [n_gates * hidden_size], attr=bias_hh_attr, is_bias=True,
                default_initializer=init)
        else:
            self.bias_hh = None

    def _cell_params(self):
        """Weights in FIXED slot order (w_ih, w_hh, b_ih, b_hh); a disabled
        bias occupies its slot as None so b_hh can never shift into the
        b_ih position when bias_ih_attr=False."""
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


def _pack_params(params):
    """Split the fixed 4-slot param list into (present tensors, unpack fn):
    only real tensors are dispatched; ``unpack`` reassembles the 4 slots
    (None where a bias is disabled) from the raw values inside the op."""
    present = [p for p in params if p is not None]
    slots = [i for i, p in enumerate(params) if p is not None]

    def unpack(raws):
        w = [None] * 4
        for s, r in zip(slots, raws):
            w[s] = r
        return w

    return present, unpack


def _gates(x, h, w_ih, w_hh, b_ih, b_hh):
    g = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    return g


def _simple_step(act):
    def step(x, state, w_ih, w_hh, b_ih, b_hh):
        h = act(_gates(x, state[0], w_ih, w_hh, b_ih, b_hh))
        return h, (h,)
    return step


def _lstm_step(x, state, w_ih, w_hh, b_ih, b_hh):
    h, c = state
    g = _gates(x, h, w_ih, w_hh, b_ih, b_hh)
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c_new = f * c + i * jnp.tanh(gg)
    h_new = o * jnp.tanh(c_new)
    return h_new, (h_new, c_new)


def _gru_step(x, state, w_ih, w_hh, b_ih, b_hh):
    h = state[0]
    gx = x @ w_ih.T
    gh = h @ w_hh.T
    if b_ih is not None:
        gx = gx + b_ih
    if b_hh is not None:
        gh = gh + b_hh
    rx, zx, cx = jnp.split(gx, 3, axis=-1)
    rh, zh, ch = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(rx + rh)
    z = jax.nn.sigmoid(zx + zh)
    c = jnp.tanh(cx + r * ch)
    h_new = z * h + (1.0 - z) * c
    return h_new, (h_new,)


class SimpleRNNCell(_GateCell):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference rnn.py:251)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1,
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
        if activation not in ("tanh", "relu"):
            raise ValueError("SimpleRNNCell activation must be tanh or relu")
        self.activation = activation
        self._act = jnp.tanh if activation == "tanh" else jax.nn.relu
        self._step = _simple_step(self._act)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        states = _ensure_tuple(states)
        params, unpack = _pack_params(self._cell_params())
        raws = [inputs] + list(states) + params

        def fn(x, *rest):
            n_state = len(states)
            st = rest[:n_state]
            out, new = self._step(x, st, *unpack(rest[n_state:]))
            return (out,) + tuple(new)

        outs = dispatch.apply(fn, *raws, op_name="rnn_cell")
        return outs[0], outs[1] if len(outs) == 2 else tuple(outs[1:])


class LSTMCell(_GateCell):
    """Gate order i,f,g,o (reference rnn.py:394,490)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 4,
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
        self._step = _lstm_step

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        params, unpack = _pack_params(self._cell_params())
        raws = [inputs, h, c] + params

        def fn(x, h, c, *w):
            out, (h2, c2) = _lstm_step(x, (h, c), *unpack(w))
            return out, h2, c2

        out, h2, c2 = dispatch.apply(fn, *raws, op_name="lstm_cell")
        return out, (h2, c2)


class GRUCell(_GateCell):
    """Gate order r,z,c; h' = z*h + (1-z)*c (reference rnn.py:557,648)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 3,
                         weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)
        self._step = _gru_step

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        states = _ensure_tuple(states)
        params, unpack = _pack_params(self._cell_params())
        raws = [inputs, states[0]] + params

        def fn(x, h, *w):
            out, (h2,) = _gru_step(x, (h,), *unpack(w))
            return out, h2

        out, h2 = dispatch.apply(fn, *raws, op_name="gru_cell")
        return out, h2


def _scan_layer(step, n_state, inputs, init_states, params, *,
                is_reverse=False, sequence_length=None, time_major=False):
    """Run one recurrent layer over the whole sequence as a single dispatched
    op built on ``lax.scan`` (TPU-idiomatic replacement for the reference's
    per-timestep python loop, rnn.py:700 RNN.forward).

    inputs: Tensor [B, T, I] (or [T, B, I] when time_major).
    init_states: tuple of Tensors [B, H].
    params: list of weight Tensors (w_ih, w_hh, [b_ih, b_hh]).
    sequence_length: optional int Tensor [B]; steps past the end keep the
    previous state and emit zeros (reference masking semantics).
    Returns (outputs, final_states tuple).
    """
    params, unpack = _pack_params(list(params))
    raws = [inputs] + list(init_states) + params
    if sequence_length is not None:
        raws.append(sequence_length)

    def fn(x, *rest):
        if sequence_length is not None:
            seq_len = rest[-1]
            rest = rest[:-1]
        else:
            seq_len = None
        st = tuple(rest[:n_state])
        w = unpack(rest[n_state:])

        xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, I]
        T = xs.shape[0]
        if is_reverse:
            xs = jnp.flip(xs, axis=0)

        def body(carry, xt):
            st, t = carry
            out, new = step(xt, st, *w)
            if seq_len is not None:
                # position in the ORIGINAL sequence
                pos = (T - 1 - t) if is_reverse else t
                valid = (pos < seq_len)[:, None]
                new = tuple(jnp.where(valid, n, s) for n, s in zip(new, st))
                out = jnp.where(valid, out, jnp.zeros_like(out))
            return (new, t + 1), out

        (final, _), outs = lax.scan(body, (st, jnp.int32(0)), xs)
        if is_reverse:
            outs = jnp.flip(outs, axis=0)
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return (outs,) + tuple(final)

    res = dispatch.apply(fn, *raws, op_name="rnn_scan")
    return res[0], tuple(res[1:])


class RNN(Layer):
    """Wrap a cell to scan over the time axis (reference rnn.py:700)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        states = _ensure_tuple(initial_states)
        outs, final = _scan_layer(
            self.cell._step, len(states), inputs, states,
            self.cell._cell_params(),
            is_reverse=self.is_reverse,
            sequence_length=sequence_length,
            time_major=self.time_major,
        )
        if len(final) == 1:
            return outs, final[0]
        return outs, final


class BiRNN(Layer):
    """Forward + backward cells; outputs concatenated (reference rnn.py:797)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major
        self._fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self._bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self._fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self._bw(inputs, st_bw, sequence_length)
        outputs = ops.concat([out_fw, out_bw], axis=-1)
        return outputs, (fin_fw, fin_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrence
    (reference rnn.py:914 RNNBase)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction in ("forward",):
            self.num_directions = 1
        elif direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        else:
            raise ValueError(f"unknown direction '{direction}'")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.direction = direction
        attrs = dict(weight_ih_attr=weight_ih_attr,
                     weight_hh_attr=weight_hh_attr,
                     bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)

        def make_cell(in_size):
            if mode == "LSTM":
                return LSTMCell(in_size, hidden_size, **attrs)
            if mode == "GRU":
                return GRUCell(in_size, hidden_size, **attrs)
            return SimpleRNNCell(in_size, hidden_size, activation=activation,
                                 **attrs)

        self._cells = []
        for layer in range(num_layers):
            in_size = input_size if layer == 0 \
                else hidden_size * self.num_directions
            for d in range(self.num_directions):
                cell = make_cell(in_size)
                name = f"cell_{layer}" if self.num_directions == 1 \
                    else f"cell_{layer}_{'fw' if d == 0 else 'bw'}"
                self.add_sublayer(name, cell)
                self._cells.append(cell)
        self.state_components = 2 if mode == "LSTM" else 1

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        n_total = self.num_layers * self.num_directions
        if initial_states is None:
            zero = lambda: ops.zeros([n_total, batch, self.hidden_size],
                                     dtype="float32")
            if self.state_components == 2:
                initial_states = (zero(), zero())
            else:
                initial_states = zero()
        states = _ensure_tuple(initial_states)

        finals = [[] for _ in range(self.state_components)]
        x = inputs
        for layer in range(self.num_layers):
            outs_dir = []
            for d in range(self.num_directions):
                idx = layer * self.num_directions + d
                cell = self._cells[idx]
                init = tuple(s[idx] for s in states)
                outs, fin = _scan_layer(
                    cell._step, self.state_components, x, init,
                    cell._cell_params(),
                    is_reverse=(d == 1),
                    sequence_length=sequence_length,
                    time_major=self.time_major,
                )
                outs_dir.append(outs)
                for k in range(self.state_components):
                    finals[k].append(fin[k])
            x = outs_dir[0] if len(outs_dir) == 1 \
                else ops.concat(outs_dir, axis=-1)
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)

        final_states = tuple(ops.stack(f, axis=0) for f in finals)
        if self.state_components == 1:
            return x, final_states[0]
        return x, final_states


class SimpleRNN(_RNNBase):
    """Reference rnn.py:1035."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    """Reference rnn.py:1157."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    """Reference rnn.py:1291."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
