"""Auto-parallel planner + cost model (reference: auto_parallel/static
completion.py dist-attr rules, tuner/parallel_tuner.py candidate search,
cost_model.py).  The plan must pick non-trivial factorizations when memory
or comm forces them, and Engine.plan must actually place parameters."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import mesh as M
from paddle_tpu.distributed.auto_parallel.planner import (
    ClusterSpec, ModelSpec, apply_placement_rules, plan)


@pytest.fixture
def clean_mesh():
    prev = M._global_mesh
    M._global_mesh = None
    yield
    M._global_mesh = prev


def test_small_model_prefers_pure_dp():
    """A model that fits one chip many times over: TP/PP only add comm
    and bubble, so pure data parallel must win."""
    m = ModelSpec(hidden=768, layers=12, seq=1024, vocab=50304, batch=64)
    cands = plan(m, ClusterSpec(n_devices=8))
    best = cands[0]
    assert best.feasible
    assert best.mesh == {"dp": 8, "mp": 1, "pp": 1}, best.mesh


def test_large_model_forced_off_pure_dp():
    """A 7B-class model cannot hold params+grads+moments on one 16 GB
    chip, so pure dp is INFEASIBLE and the winner uses mp and/or pp."""
    m = ModelSpec(hidden=4096, layers=32, seq=1024, vocab=50304, batch=16)
    cands = plan(m, ClusterSpec(n_devices=8))
    by_mesh = {tuple(sorted(c.mesh.items())): c for c in cands}
    pure_dp = by_mesh[tuple(sorted({"dp": 8, "mp": 1, "pp": 1}.items()))]
    assert not pure_dp.feasible
    best = cands[0]
    assert best.feasible, [c.reason for c in cands[:3]]
    assert best.mesh["mp"] * best.mesh["pp"] > 1, best.mesh


def test_cost_estimates_monotone_in_comm():
    """More TP on the same workload means more activation all-reduce
    time; the model must reflect that."""
    m = ModelSpec(hidden=2048, layers=24, seq=1024, vocab=50304, batch=32)
    c = ClusterSpec(n_devices=8)
    cands = {tuple(sorted(x.mesh.items())): x for x in plan(m, c)}
    mp2 = cands[tuple(sorted({"dp": 4, "mp": 2, "pp": 1}.items()))]
    mp8 = cands[tuple(sorted({"dp": 1, "mp": 8, "pp": 1}.items()))]
    assert mp8.tp_comm_time > mp2.tp_comm_time > 0


def test_engine_cost_returns_candidates(clean_mesh):
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models import GPTPretrainingCriterion, GPTForPretraining, gpt_tiny

    cfg = gpt_tiny()
    pt.seed(0)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    engine = Engine(model=model, loss=GPTPretrainingCriterion(cfg),
                    optimizer=opt)
    out = engine.cost(cluster=ClusterSpec(n_devices=8))
    assert out["best"] is not None
    assert len(out["candidates"]) == len(plan(
        ModelSpec(hidden=1, layers=1, seq=1, vocab=1, batch=1),
        ClusterSpec(n_devices=8)))
    for c in out["candidates"]:
        assert {"mesh", "step_time", "mem_bytes", "feasible"} <= set(c)


def test_engine_plan_places_params_and_trains(clean_mesh):
    """Engine.plan picks a mesh, installs it, Megatron-places the params
    (embedding vocab-parallel + alternating row/col linears), and fit
    still trains.  Forced onto an mp-heavy cluster by a tiny fake HBM."""
    from paddle_tpu.distributed.auto_parallel import Engine, Strategy
    from paddle_tpu.models import GPTPretrainingCriterion, GPTForPretraining, gpt_tiny

    cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    pt.seed(0)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    engine = Engine(model=model, loss=GPTPretrainingCriterion(cfg),
                    optimizer=opt, strategy=Strategy())
    # HBM small enough that pure dp8 of even the tiny model is infeasible
    n_bytes = sum(int(np.prod(p.shape)) for p in model.parameters()) * 8
    best = engine.plan(cluster=ClusterSpec(n_devices=8,
                                           hbm_bytes=n_bytes / 2))
    assert best.mesh["mp"] * best.mesh["pp"] > 1, best.mesh
    assert M.has_mesh()
    sharded = [p for p in model.parameters()
               if any(ax is not None for ax in
                      getattr(p._value.sharding, "spec", []) or [])]
    if best.mesh.get("mp", 1) > 1:
        assert sharded, "plan() chose mp>1 but placed no parameters"

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 16))
    hist = engine.fit([(ids, ids) for _ in range(4)], epochs=1, verbose=0)
    assert np.isfinite(hist["loss"]).all()
    assert hist["loss"][-1] < hist["loss"][0]
