// TCPStore: socket key-value store for multi-host rendezvous.
//
// Native C++ equivalent of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:120, tcp_utils.cc) — the
// bootstrap KV used to exchange coordinator addresses before the XLA
// distributed runtime comes up. Exposed through a C ABI consumed by
// ctypes (paddle_tpu/core/native/tcp_store.py); no pybind dependency.
//
// Protocol (little-endian):
//   request : u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   response: u32 vlen | value bytes   (vlen == 0xFFFFFFFF => not found)
// Ops: 0=SET 1=GET(blocking-wait) 2=ADD(returns new i64) 3=CHECK 4=DELETE
//      5=WAIT(value = i64 timeout_ms; returns u8 1=found 0=timeout)
//      6=LIST(key = prefix; resp = u32 count | (u32 klen | key bytes)*)
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> data;
  std::atomic<bool> running{true};
  int listen_fd = -1;
  uint16_t bound_port = 0;
  std::thread accept_thread;
  // workers is mutated only by the accept thread (stop() joins it first);
  // client_fds is registered by the accept thread and de-registered by each
  // worker on disconnect, both under conn_mu.
  std::mutex conn_mu;
  std::vector<std::thread> workers;
  std::vector<int> client_fds;
};

bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void serve_client(Store* st, int fd) {
  for (;;) {
    uint8_t op;
    if (!read_full(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_full(fd, &klen, 4) || klen > (1u << 20)) break;
    std::string key(klen, '\0');
    if (!read_full(fd, key.data(), klen)) break;
    uint32_t vlen;
    if (!read_full(fd, &vlen, 4) || (vlen != 0 && vlen > (1u << 28))) break;
    std::vector<uint8_t> val(vlen);
    if (vlen && !read_full(fd, val.data(), vlen)) break;

    std::vector<uint8_t> resp;
    if (op == 0) {  // SET
      {
        std::lock_guard<std::mutex> lk(st->mu);
        st->data[key] = std::move(val);
      }
      st->cv.notify_all();
    } else if (op == 1) {  // GET (blocking wait until key exists)
      std::unique_lock<std::mutex> lk(st->mu);
      st->cv.wait(lk, [&] { return !st->running || st->data.count(key); });
      if (!st->running) break;
      resp = st->data[key];
    } else if (op == 2) {  // ADD: value = i64 delta; returns new value
      int64_t delta = 0;
      if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
      int64_t cur = 0;
      {
        std::lock_guard<std::mutex> lk(st->mu);
        auto it = st->data.find(key);
        if (it != st->data.end() && it->second.size() == 8)
          std::memcpy(&cur, it->second.data(), 8);
        cur += delta;
        std::vector<uint8_t> nv(8);
        std::memcpy(nv.data(), &cur, 8);
        st->data[key] = nv;
      }
      st->cv.notify_all();
      resp.resize(8);
      std::memcpy(resp.data(), &cur, 8);
    } else if (op == 3) {  // CHECK (non-blocking)
      std::lock_guard<std::mutex> lk(st->mu);
      uint8_t found = st->data.count(key) ? 1 : 0;
      resp.assign(1, found);
    } else if (op == 4) {  // DELETE
      std::lock_guard<std::mutex> lk(st->mu);
      st->data.erase(key);
    } else if (op == 5) {  // WAIT with timeout (ms); resp = u8 found | value
      // The value rides along in the response so the caller needs no
      // follow-up GET (which could block forever if the key is deleted
      // between the two round trips).
      int64_t timeout_ms = -1;
      if (val.size() == 8) std::memcpy(&timeout_ms, val.data(), 8);
      std::unique_lock<std::mutex> lk(st->mu);
      bool found;
      auto pred = [&] { return !st->running || st->data.count(key); };
      if (timeout_ms < 0) {
        st->cv.wait(lk, pred);
        found = st->data.count(key) != 0;
      } else {
        found = st->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred) &&
                st->data.count(key) != 0;
      }
      if (!st->running) break;
      resp.assign(1, found ? 1 : 0);
      if (found) {
        const auto& v = st->data[key];
        resp.insert(resp.end(), v.begin(), v.end());
      }
    } else if (op == 6) {  // LIST keys with prefix (generation sweeps +
      // the fault gate's key accounting; non-blocking)
      std::lock_guard<std::mutex> lk(st->mu);
      uint32_t count = 0;
      resp.resize(4);
      for (const auto& kv : st->data) {
        if (kv.first.compare(0, key.size(), key) != 0) continue;
        ++count;
        uint32_t klen2 = static_cast<uint32_t>(kv.first.size());
        const uint8_t* p = reinterpret_cast<const uint8_t*>(&klen2);
        resp.insert(resp.end(), p, p + 4);
        resp.insert(resp.end(), kv.first.begin(), kv.first.end());
      }
      std::memcpy(resp.data(), &count, 4);
    } else {
      break;
    }
    uint32_t rlen = static_cast<uint32_t>(resp.size());
    if (!write_full(fd, &rlen, 4)) break;
    if (rlen && !write_full(fd, resp.data(), rlen)) break;
  }
  // De-register before closing: the kernel may recycle this fd number for an
  // unrelated socket, and stop() must not shutdown() a recycled fd.
  {
    std::lock_guard<std::mutex> lk(st->conn_mu);
    auto& fds = st->client_fds;
    for (auto it = fds.begin(); it != fds.end(); ++it) {
      if (*it == fd) {
        fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// ---- server -------------------------------------------------------------
void* tcp_store_server_start(uint16_t port) {
  auto* st = new Store();
  st->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (st->listen_fd < 0) {
    delete st;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(st->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(st->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(st->listen_fd, 128) != 0) {
    ::close(st->listen_fd);
    delete st;
    return nullptr;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  if (::getsockname(st->listen_fd, reinterpret_cast<sockaddr*>(&bound), &blen) == 0)
    st->bound_port = ntohs(bound.sin_port);
  st->accept_thread = std::thread([st] {
    while (st->running) {
      int fd = ::accept(st->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(st->conn_mu);
        st->client_fds.push_back(fd);
      }
      st->workers.emplace_back(serve_client, st, fd);
    }
  });
  return st;
}

uint16_t tcp_store_server_port(void* handle) {
  auto* st = static_cast<Store*>(handle);
  return st ? st->bound_port : 0;
}

void tcp_store_server_stop(void* handle) {
  auto* st = static_cast<Store*>(handle);
  if (!st) return;
  // Flip `running` UNDER mu: a worker between its pred evaluation and the cv
  // block would otherwise miss the notify and sleep forever (and the join
  // below would deadlock).
  {
    std::lock_guard<std::mutex> lk(st->mu);
    st->running = false;
  }
  st->cv.notify_all();
  ::shutdown(st->listen_fd, SHUT_RDWR);
  ::close(st->listen_fd);
  if (st->accept_thread.joinable()) st->accept_thread.join();
  // Unblock workers stuck in recv() by shutting their sockets down, then
  // JOIN them all before freeing the Store — a detached worker touching the
  // freed mutex/cv/map was a use-after-free.
  {
    std::lock_guard<std::mutex> lk(st->conn_mu);
    for (int fd : st->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  st->cv.notify_all();
  for (auto& w : st->workers)
    if (w.joinable()) w.join();
  delete st;
}

// ---- client -------------------------------------------------------------
int tcp_store_connect(const char* host, uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

static int request(int fd, uint8_t op, const char* key, uint32_t klen,
                   const uint8_t* val, uint32_t vlen, uint8_t** out,
                   uint32_t* out_len) {
  if (!write_full(fd, &op, 1)) return -1;
  if (!write_full(fd, &klen, 4)) return -1;
  if (klen && !write_full(fd, key, klen)) return -1;
  if (!write_full(fd, &vlen, 4)) return -1;
  if (vlen && !write_full(fd, val, vlen)) return -1;
  uint32_t rlen;
  if (!read_full(fd, &rlen, 4)) return -1;
  *out_len = rlen;
  *out = nullptr;
  if (rlen) {
    *out = static_cast<uint8_t*>(::malloc(rlen));
    if (!read_full(fd, *out, rlen)) {
      ::free(*out);
      return -1;
    }
  }
  return 0;
}

int tcp_store_set(int fd, const char* key, const uint8_t* val, uint32_t vlen) {
  uint8_t* out;
  uint32_t olen;
  return request(fd, 0, key, static_cast<uint32_t>(strlen(key)), val, vlen,
                 &out, &olen);
}

int tcp_store_get(int fd, const char* key, uint8_t** out, uint32_t* out_len) {
  return request(fd, 1, key, static_cast<uint32_t>(strlen(key)), nullptr, 0,
                 out, out_len);
}

int tcp_store_delete(int fd, const char* key) {
  uint8_t* out;
  uint32_t olen;
  int rc = request(fd, 4, key, static_cast<uint32_t>(strlen(key)), nullptr, 0,
                   &out, &olen);
  if (out) ::free(out);
  return rc;
}

// Returns 0 on success with *result set (out-param so legitimate negative
// counter values are not misread as failures), -1 on transport error.
int tcp_store_add(int fd, const char* key, int64_t delta, int64_t* result) {
  uint8_t buf[8];
  std::memcpy(buf, &delta, 8);
  uint8_t* out;
  uint32_t olen;
  if (request(fd, 2, key, static_cast<uint32_t>(strlen(key)), buf, 8, &out,
              &olen) != 0 || olen != 8)
    return -1;
  std::memcpy(result, out, 8);
  ::free(out);
  return 0;
}

// 1 = key present (*out/*out_len hold the value, caller frees), 0 = timed
// out, -1 = transport error.  timeout_ms < 0 blocks indefinitely.
int tcp_store_wait(int fd, const char* key, int64_t timeout_ms, uint8_t** out,
                   uint32_t* out_len) {
  uint8_t buf[8];
  std::memcpy(buf, &timeout_ms, 8);
  uint8_t* resp;
  uint32_t rlen;
  *out = nullptr;
  *out_len = 0;
  if (request(fd, 5, key, static_cast<uint32_t>(strlen(key)), buf, 8, &resp,
              &rlen) != 0 || rlen < 1)
    return -1;
  int found = resp[0];
  if (found && rlen > 1) {
    *out_len = rlen - 1;
    *out = static_cast<uint8_t*>(::malloc(rlen - 1));
    std::memcpy(*out, resp + 1, rlen - 1);
  }
  ::free(resp);
  return found;
}

// Non-blocking key listing: *out is the raw framed response
// (u32 count | (u32 klen | key bytes)*), parsed by the python surface.
int tcp_store_list(int fd, const char* prefix, uint8_t** out,
                   uint32_t* out_len) {
  return request(fd, 6, prefix, static_cast<uint32_t>(strlen(prefix)), nullptr,
                 0, out, out_len);
}

int tcp_store_check(int fd, const char* key) {
  uint8_t* out;
  uint32_t olen;
  if (request(fd, 3, key, static_cast<uint32_t>(strlen(key)), nullptr, 0, &out,
              &olen) != 0 || olen != 1)
    return -1;
  int v = out[0];
  ::free(out);
  return v;
}

void tcp_store_close(int fd) { ::close(fd); }

void tcp_store_free(uint8_t* p) { ::free(p); }

}  // extern "C"
