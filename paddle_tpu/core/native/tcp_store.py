"""TCPStore python surface over the native C++ store (reference:
paddle/phi/core/distributed/store/tcp_store.h:120). Falls back to an
in-process dict store when the native library is unavailable (keeps
single-host tests hermetic).

All retry/wait deadlines use ``time.monotonic()`` — an NTP step or
wall-clock jump must neither hang a bounded wait nor expire it
instantly (same discipline as serving/engine.py's deadlines)."""
from __future__ import annotations

import ctypes
import socket
import threading
import time
from typing import Optional

from .build import load_native

__all__ = ["TCPStore"]


def _lib():
    lib = load_native("tcp_store")
    if lib is None:
        return None
    lib.tcp_store_server_start.restype = ctypes.c_void_p
    lib.tcp_store_server_start.argtypes = [ctypes.c_uint16]
    lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.tcp_store_connect.restype = ctypes.c_int
    lib.tcp_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.tcp_store_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32]
    lib.tcp_store_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                                  ctypes.POINTER(ctypes.c_uint32)]
    lib.tcp_store_delete.restype = ctypes.c_int
    lib.tcp_store_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.tcp_store_add.restype = ctypes.c_int
    lib.tcp_store_add.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64)]
    lib.tcp_store_wait.restype = ctypes.c_int
    lib.tcp_store_wait.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                                   ctypes.POINTER(ctypes.c_uint32)]
    lib.tcp_store_server_port.restype = ctypes.c_uint16
    lib.tcp_store_server_port.argtypes = [ctypes.c_void_p]
    lib.tcp_store_check.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.tcp_store_close.argtypes = [ctypes.c_int]
    lib.tcp_store_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    return lib


class TCPStore:
    """KV + counter store. is_master=True also hosts the server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1, timeout: float = 60.0):
        self._lib = _lib()
        self._server = None
        self._fd = None
        self._local: Optional[dict] = None
        # the wire protocol is strict request/response on ONE socket —
        # concurrent callers (elastic heartbeat + watcher threads) must
        # serialize or responses interleave and both block
        self._io_lock = threading.Lock()
        self.host, self.port = host, port
        if self._lib is None:
            # pure-python single-process fallback
            self._local = {}
            self._lock = threading.Lock()
            return
        if is_master:
            self._server = self._lib.tcp_store_server_start(ctypes.c_uint16(port))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            # port=0 binds an ephemeral port; surface the real one
            self.port = port = int(self._lib.tcp_store_server_port(self._server))
        deadline = time.monotonic() + timeout
        while True:
            self._fd = self._lib.tcp_store_connect(host.encode(), ctypes.c_uint16(port))
            if self._fd >= 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(f"TCPStore: cannot connect {host}:{port}")
            time.sleep(0.05)

    # -- KV ----------------------------------------------------------------
    def set(self, key: str, value: bytes):
        if self._local is not None:
            with self._lock:
                self._local[key] = bytes(value)
            return
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) if value else None
        with self._io_lock:
            rc = self._lib.tcp_store_set(self._fd, key.encode(), buf, len(value))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        if self._local is not None:
            deadline = time.monotonic() + 60
            while True:
                with self._lock:
                    if key in self._local:
                        return self._local[key]
                if time.monotonic() > deadline:
                    raise TimeoutError(f"key {key} never set")
                time.sleep(0.01)
        out = ctypes.POINTER(ctypes.c_uint8)()
        olen = ctypes.c_uint32()
        with self._io_lock:
            rc = self._lib.tcp_store_get(self._fd, key.encode(),
                                         ctypes.byref(out), ctypes.byref(olen))
        if rc != 0:
            raise RuntimeError("TCPStore.get failed")
        data = ctypes.string_at(out, olen.value) if olen.value else b""
        if olen.value:
            self._lib.tcp_store_free(out)
        return data

    def add(self, key: str, delta: int = 1) -> int:
        if self._local is not None:
            with self._lock:
                cur = int.from_bytes(self._local.get(key, b"\0" * 8), "little", signed=True)
                cur += delta
                self._local[key] = cur.to_bytes(8, "little", signed=True)
                return cur
        result = ctypes.c_int64()
        with self._io_lock:
            rc = self._lib.tcp_store_add(self._fd, key.encode(), delta,
                                         ctypes.byref(result))
        if rc != 0:
            raise RuntimeError("TCPStore.add failed")
        return int(result.value)

    def delete(self, key: str):
        """Remove a key (server op 4) — used by consumers (e.g. cross-host
        recv) so long-running jobs don't grow the master store unboundedly."""
        if self._local is not None:
            with self._lock:
                self._local.pop(key, None)
            return
        with self._io_lock:
            rc = self._lib.tcp_store_delete(self._fd, key.encode())
        if rc != 0:
            raise RuntimeError("TCPStore.delete failed")

    def check(self, key: str) -> bool:
        if self._local is not None:
            with self._lock:
                return key in self._local
        with self._io_lock:
            return self._lib.tcp_store_check(self._fd, key.encode()) == 1

    def wait(self, key: str, timeout: float = 60.0) -> bytes:
        """Block until ``key`` exists (up to ``timeout`` seconds), then return
        its value. Raises TimeoutError if the key never arrives."""
        if self._local is not None:
            deadline = time.monotonic() + timeout
            while True:
                with self._lock:
                    if key in self._local:
                        return self._local[key]
                if time.monotonic() > deadline:
                    raise TimeoutError(f"TCPStore.wait: key {key!r} not set "
                                       f"within {timeout}s")
                time.sleep(0.01)
        # A single long server-side wait would hold _io_lock for the whole
        # blocking period (up to an hour for p2p), starving every other
        # thread on this store — e.g. the elastic heartbeat, whose missed
        # beats would look like a dead node.  Poll with SHORT server-side
        # waits instead, releasing the lock between polls.
        deadline = time.monotonic() + timeout
        while True:
            slice_ms = int(min(0.2, max(0.0, deadline - time.monotonic())) * 1000)
            out = ctypes.POINTER(ctypes.c_uint8)()
            olen = ctypes.c_uint32()
            with self._io_lock:
                rc = self._lib.tcp_store_wait(self._fd, key.encode(),
                                              ctypes.c_int64(slice_ms),
                                              ctypes.byref(out), ctypes.byref(olen))
            if rc < 0:
                raise RuntimeError("TCPStore.wait failed")
            if rc > 0:
                data = ctypes.string_at(out, olen.value) if olen.value else b""
                if olen.value:
                    self._lib.tcp_store_free(out)
                return data
            if time.monotonic() >= deadline:
                raise TimeoutError(f"TCPStore.wait: key {key!r} not set within "
                                   f"{timeout}s")

    def barrier(self, name: str, world_size: int, timeout: float = 60.0):
        """Counter barrier: every rank adds 1 then waits for world_size."""
        n = self.add(f"__barrier__/{name}", 1)
        deadline = time.monotonic() + timeout
        while n < world_size:
            time.sleep(0.02)
            n = self.add(f"__barrier__/{name}", 0)
            if time.monotonic() > deadline:
                raise TimeoutError(f"barrier {name}: {n}/{world_size}")

    def __del__(self):
        try:
            if self._lib is not None and self._fd is not None and self._fd >= 0:
                self._lib.tcp_store_close(self._fd)
            if self._lib is not None and self._server:
                self._lib.tcp_store_server_stop(self._server)
        except Exception:
            pass
