"""Higher-order autograd: create_graph, double grad, jacobian, hessian.

Reference analog: test/legacy_test/test_imperative_double_grad.py and
python/paddle/autograd/autograd.py Jacobian/Hessian tests.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import autograd


def test_double_grad_scalar():
    # y = x^3 -> dy/dx = 3x^2 -> d2y/dx2 = 6x
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (dx,) = autograd.grad([y], [x], create_graph=True)
    assert dx.stop_gradient is False
    np.testing.assert_allclose(float(dx), 12.0, rtol=1e-6)
    (ddx,) = autograd.grad([dx], [x])
    np.testing.assert_allclose(float(ddx), 12.0, rtol=1e-6)


def test_double_grad_vector():
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    x = pt.to_tensor(xv, stop_gradient=False)
    y = pt.ops.sum(x * x * x)
    (dx,) = autograd.grad([y], [x], create_graph=True)
    np.testing.assert_allclose(dx.numpy(), 3 * xv**2, rtol=1e-5)
    z = pt.ops.sum(dx)
    (ddx,) = autograd.grad([z], [x])
    np.testing.assert_allclose(ddx.numpy(), 6 * xv, rtol=1e-5)


def test_triple_grad():
    x = pt.to_tensor(1.5, stop_gradient=False)
    y = x * x * x * x  # y = x^4
    (d1,) = autograd.grad([y], [x], create_graph=True)
    (d2,) = autograd.grad([d1], [x], create_graph=True)
    (d3,) = autograd.grad([d2], [x])
    np.testing.assert_allclose(float(d3), 24 * 1.5, rtol=1e-5)  # 24x


def test_double_grad_through_matmul():
    a = np.random.RandomState(0).randn(3, 3).astype(np.float32)
    x = pt.to_tensor(a, stop_gradient=False)
    y = pt.ops.sum(pt.ops.matmul(x, x))
    (dx,) = autograd.grad([y], [x], create_graph=True)
    # d/dX sum(X@X) = (X@X grad): ones@X^T + X^T@ones
    ones = np.ones((3, 3), np.float32)
    expected = ones @ a.T + a.T @ ones
    np.testing.assert_allclose(dx.numpy(), expected, rtol=1e-5)
    z = pt.ops.sum(dx * dx)
    (ddx,) = autograd.grad([z], [x])
    assert ddx.shape == [3, 3]
    assert np.isfinite(ddx.numpy()).all()


def test_hessian_quadratic():
    # f(x) = x^T A x  ->  H = A + A^T
    rng = np.random.RandomState(1)
    a = rng.randn(4, 4).astype(np.float32)
    A = pt.to_tensor(a)
    x = pt.to_tensor(rng.randn(4).astype(np.float32), stop_gradient=False)
    y = pt.ops.sum(x * pt.ops.matmul(A, x))
    H = autograd.hessian(y, x)
    np.testing.assert_allclose(H.numpy(), a + a.T, rtol=1e-4, atol=1e-5)


def test_jacobian_linear():
    rng = np.random.RandomState(2)
    a = rng.randn(3, 5).astype(np.float32)
    A = pt.to_tensor(a)
    x = pt.to_tensor(rng.randn(5).astype(np.float32), stop_gradient=False)
    y = pt.ops.matmul(A, x)
    J = autograd.jacobian(y, x)
    np.testing.assert_allclose(J.numpy(), a, rtol=1e-5, atol=1e-6)


def test_vjp_jvp():
    rng = np.random.RandomState(3)
    xv = rng.randn(4).astype(np.float32)
    vv = rng.randn(4).astype(np.float32)

    def f(x):
        return pt.ops.sum(x * x)

    x = pt.to_tensor(xv, stop_gradient=False)
    v = pt.to_tensor(np.float32(1.0))
    _, g = autograd.vjp(f, x, v)
    np.testing.assert_allclose(g.numpy(), 2 * xv, rtol=1e-5)

    x2 = pt.to_tensor(xv, stop_gradient=False)
    _, tangent = autograd.jvp(f, x2, pt.to_tensor(vv))
    np.testing.assert_allclose(float(tangent), float((2 * xv * vv).sum()), rtol=1e-4)


def test_grad_no_create_graph_still_raw():
    x = pt.to_tensor(3.0, stop_gradient=False)
    y = x * x
    (dx,) = autograd.grad([y], [x])
    assert dx.stop_gradient is True
    np.testing.assert_allclose(float(dx), 6.0, rtol=1e-6)
