"""PyLayer: user-defined forward/backward pairs.

Reference: python/paddle/autograd/py_layer.py:29,234 and C++
paddle/fluid/eager/pylayer/. TPU-native: the user's backward is spliced into
the grad graph as a custom GradNode whose "vjp" calls the python staticmethod;
under jit tracing the python backward traces into the same XLA program.
"""
from __future__ import annotations

from typing import Any, List

import numpy as np

from ..core import dtype as _dtype_mod

from ..tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.extra = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    # attribute bag like the reference ctx
    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..autograd.engine import GradNode
        from ..ops import dispatch

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = dispatch.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )

        with dispatch.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)

        if not needs_grad:
            return outputs

        def vjp_fn(cotangents):
            cts = [Tensor(c, stop_gradient=True) for c in cotangents]
            with dispatch.no_grad():
                grads = cls.backward(ctx, *cts)
            if not isinstance(grads, (tuple, list)):
                grads = [grads]
            raw = []
            gi = iter(grads)
            for a in args:
                if isinstance(a, Tensor):
                    g = next(gi, None)
                    raw.append(g._value if isinstance(g, Tensor) else g)
            return tuple(raw)

        node = GradNode(
            vjp_fn=vjp_fn,
            inputs=tuple(tensor_inputs),
            out_avals=tuple((o._value.shape, o._value.dtype) for o in outs),
            name=cls.__name__,
        )
        import weakref

        wrapped = []
        for i, o in enumerate(outs):
            t = Tensor(o._value, stop_gradient=False)
            if _dtype_mod.is_inexact_raw(o._value.dtype):
                t._grad_node = node
                t._output_index = i
            else:
                t.stop_gradient = True
            node._out_tensors.append(weakref.ref(t))
            wrapped.append(t)
        return wrapped[0] if single else tuple(wrapped)
