"""Int8 KV-page quantization: write-quantize / read-dequant helpers.

The serving engine's paged KV pool can store pages as int8 with ONE
fp32 absmax scale per (page, head) in a parallel ``[num_pages, H]``
buffer (serving/paged_cache.py ``kv_dtype="int8"``).  This module owns
the in-graph write-side quantizer; the read side lives INSIDE the
attention kernels (ops/pallas_kernels/*_attention.py dequantize each
page right after its DMA, so dequantized values never round-trip HBM).

Scale update contract ("fresh-page step-absmax, stale-page clip" —
docs/serving.md "Quantized serving"):

- A page is FRESH in a step when the step writes its offset-0 row (a
  page's first write always lands at offset 0: admission hands out
  whole pages, the prefix cache splices only FULL pages, so every
  owner starts writing at its page boundary), or when its scale is
  still the zero-initialized sentinel.  A fresh page's scale becomes
  the per-head absmax/127 over ALL tokens the step writes into it —
  for whole-page prefill that is the exact page absmax.
- A STALE page (later decode tokens trickling into a partially filled
  page) keeps its existing scale; new tokens clip into ±127.

The update is built from commutative scatter ops (``mul`` by {0,1} to
reset fresh rows, then scatter-``max`` of the step contributions), so
it is deterministic under duplicate indices and identical token
sequences produce bitwise-identical pages AND scales — the property
the prefix cache's copy-on-write page adoption relies on.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["TINY_SCALE", "quantize_kv_write", "dequant_pages"]

# floor for effective scales: an all-zero page dequantizes to zeros
# instead of dividing by zero, and real absmax contributions stay
# strictly positive so the freshness sentinel (scale == 0.0) is
# unambiguous
TINY_SCALE = 1e-8


def quantize_kv_write(x, page_ids, offs, scale):
    """Quantize one step's KV scatter values; update per-page scales.

    x: ``[S, C, H, D]`` float values about to be scattered to
    ``pool[page_ids, :, offs, :]``; ``page_ids`` / ``offs``:
    ``[S, C]`` int32 (padding rows point at the null page — its scale
    row absorbs their updates and is never read validly); ``scale``:
    ``[P, H]`` fp32 per-(page, head) scales.

    Returns ``(q, new_scale)`` where ``q`` is the int8 payload for the
    same scatter and ``new_scale`` the updated ``[P, H]`` buffer.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)               # [S, C, H]
    contrib = absmax / jnp.float32(127.0) + jnp.float32(TINY_SCALE)
    fresh = (offs == 0)                                  # [S, C]
    # reset fresh pages' scale rows (stale entries multiply the null
    # page's row by 1.0 — a no-op)
    tgt = jnp.where(fresh, page_ids, 0)
    keep = jnp.where(fresh, jnp.float32(0.0), jnp.float32(1.0))
    s1 = scale.at[tgt].mul(keep[..., None])
    # freshness per (token, head) AFTER the reset: covers both the
    # offset-0 writers and never-written pages (zero-init sentinel)
    is_fresh = jnp.take(s1, page_ids, axis=0) == jnp.float32(0.0)
    s2 = s1.at[page_ids].max(
        jnp.where(is_fresh, contrib, jnp.float32(0.0)))
    s_eff = jnp.maximum(jnp.take(s2, page_ids, axis=0),
                        jnp.float32(TINY_SCALE))         # [S, C, H]
    q = jnp.clip(jnp.round(xf / s_eff[..., None]),
                 -127.0, 127.0).astype(jnp.int8)
    return q, s2


def dequant_pages(pool, scale):
    """``[P, H, ps, D]`` int8 pages x ``[P, H]`` scales -> fp32.

    The XLA oracle path (and tests) — the Pallas kernels do the same
    multiply per page INSIDE the kernel body instead.
    """
    return pool.astype(jnp.float32) * scale[:, :, None, None]
