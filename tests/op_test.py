"""OpTest analog (reference: test/legacy_test/eager_op_test.py:377 —
check_output against numpy references across execution modes; check_grad
analytic vs numeric)."""
from __future__ import annotations

import numpy as np

import paddle_tpu
from paddle_tpu import Tensor


def check_output(op_fn, numpy_fn, inputs, rtol=1e-5, atol=1e-6, modes=("eager", "static"), **op_kwargs):
    """Run op_fn over Tensor inputs in eager + to_static modes; compare with
    numpy_fn over raw arrays."""
    np_inputs = [np.asarray(i) for i in inputs]
    expect = numpy_fn(*np_inputs)
    results = {}
    if "eager" in modes:
        ts = [paddle_tpu.to_tensor(i) for i in np_inputs]
        results["eager"] = op_fn(*ts, **op_kwargs)
    if "static" in modes:
        ts = [paddle_tpu.to_tensor(i) for i in np_inputs]
        static_fn = paddle_tpu.jit.to_static(lambda *a: op_fn(*a, **op_kwargs))
        static_fn(*ts)  # warmup
        static_fn(*ts)  # scout+compile
        results["static"] = static_fn(*ts)  # compiled
    for mode, out in results.items():
        if isinstance(out, (tuple, list)):
            outs = out
            expects = expect if isinstance(expect, (tuple, list)) else [expect]
        else:
            outs = [out]
            expects = [expect]
        for o, e in zip(outs, expects):
            np.testing.assert_allclose(
                o.numpy().astype(np.float64) if np.issubdtype(np.asarray(e).dtype, np.floating) else o.numpy(),
                np.asarray(e),
                rtol=rtol,
                atol=atol,
                err_msg=f"mode={mode}",
            )


def check_grad(op_fn, inputs, output_grad=None, rtol=1e-3, atol=1e-4, eps=1e-3, **op_kwargs):
    """Numeric-vs-analytic gradient check (reference check_grad:2323)."""
    np_inputs = [np.asarray(i, dtype=np.float64) for i in inputs]

    def f(*arrays):
        ts = [paddle_tpu.to_tensor(a.astype(np.float64), stop_gradient=False) for a in arrays]
        out = op_fn(*ts, **op_kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out

    # analytic
    ts = [paddle_tpu.to_tensor(a, stop_gradient=False) for a in np_inputs]
    out = op_fn(*ts, **op_kwargs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    og = (
        np.ones(out.shape, np.float64)
        if output_grad is None
        else np.asarray(output_grad, np.float64)
    )
    out.backward(paddle_tpu.to_tensor(og))
    analytic = [t.grad.numpy() if t.grad is not None else np.zeros_like(a) for t, a in zip(ts, np_inputs)]

    # numeric central difference
    for idx, base in enumerate(np_inputs):
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        nf = num.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            hi = float((f(*np_inputs).numpy() * og).sum())
            flat[i] = orig - eps
            lo = float((f(*np_inputs).numpy() * og).sum())
            flat[i] = orig
            nf[i] = (hi - lo) / (2 * eps)
        np.testing.assert_allclose(analytic[idx], num, rtol=rtol, atol=atol,
                                   err_msg=f"grad wrt input {idx}")
