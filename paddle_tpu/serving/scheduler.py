"""Compatibility facade over the PR-14 scheduler split.

``serving/scheduler.py`` historically held the one slot scheduler.  It is
now two layers (docs/serving.md "Sharded serving"):

- :mod:`serving.admission` — per-replica: slots, up-front page
  reservation, per-step token planning, retirement (the class that used
  to live here, now ``AdmissionScheduler`` with the old ``Scheduler``
  name kept as an alias);
- :mod:`serving.placement` — cluster-level: which ``dp`` replica seats a
  request (least-loaded, queue-depth backpressure signal; sheds only when
  every replica does).

Import sites that predate the split keep working through this module.
"""
from .admission import (  # noqa: F401
    AdmissionScheduler,
    Scheduler,
    Slot,
    StepWork,
)
from .paged_cache import pages_for_tokens  # noqa: F401
from .placement import (  # noqa: F401
    LeastLoadedPlacement,
    PlacementScheduler,
    PrefixLocalityPlacement,
    replica_load,
)
from .prefix_cache import PrefixCache  # noqa: F401

__all__ = [
    "AdmissionScheduler", "Scheduler", "Slot", "StepWork",
    "LeastLoadedPlacement", "PlacementScheduler", "PrefixLocalityPlacement",
    "PrefixCache", "pages_for_tokens", "replica_load",
]
