"""framework: save/load, RNG seeding, misc runtime glue
(reference: python/paddle/framework/)."""
from . import io  # noqa: F401
from .io import load, save  # noqa: F401
from ..ops.random import seed  # noqa: F401
from ..ops.dispatch import is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
