"""Owned Pallas fused residual-add + RMS/LayerNorm kernels (reference
fusion/fused_bias_residual_layernorm analog) — interpret-mode parity
with row counts ABOVE the eligibility gate so the kernels actually
execute (the CPU check discipline used for flash-attn and fused AdamW)."""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas_kernels.rms_norm import (
    _ln_reference, _pick_rows, _reference, fused_add_layer_norm,
    fused_add_rms_norm, shape_supported)

ROWS = 16          # >= 8: the pallas path engages under interpret=True


def test_fused_add_rms_norm_interpret_parity():
    assert _pick_rows(ROWS, 256) >= 8      # kernel path, not fallback
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(ROWS, 256).astype(np.float32))
    r = jnp.asarray(rng.randn(ROWS, 256).astype(np.float32))
    g = jnp.asarray(rng.randn(256).astype(np.float32))
    out, h = fused_add_rms_norm(x, r, g, 1e-6, True)
    ref_out, ref_h = _reference(x, r, g, 1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), np.asarray(ref_h))

    def loss(fn):
        def inner(a, b, c):
            o, hh = fn(a, b, c)
            return jnp.sum(o * o) + jnp.sum(hh)
        return inner

    g1 = jax.grad(loss(lambda a, b, c: fused_add_rms_norm(
        a, b, c, 1e-6, True)), argnums=(0, 1, 2))(x, r, g)
    g2 = jax.grad(loss(lambda a, b, c: _reference(a, b, c, 1e-6)),
                  argnums=(0, 1, 2))(x, r, g)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


def test_fused_add_layer_norm_interpret_parity():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, ROWS, 128).astype(np.float32))
    r = jnp.asarray(rng.randn(2, ROWS, 128).astype(np.float32))
    g = jnp.asarray(rng.randn(128).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    out, h = fused_add_layer_norm(x, r, g, b, 1e-5, True)
    ro, rh = _ln_reference(x, r, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), np.asarray(rh))
    g1 = jax.grad(lambda a: jnp.sum(
        fused_add_layer_norm(a, r, g, b, 1e-5, True)[0] ** 2))(x)
    g2 = jax.grad(lambda a: jnp.sum(
        _ln_reference(a, r, g, b, 1e-5)[0] ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=1e-4)


def test_block_sizing_and_fallbacks():
    # VMEM-aware cap: 8 MiB / (16 * hdim)
    assert _pick_rows(1024, 8192) <= (8 * 2 ** 20) // (16 * 8192)
    assert _pick_rows(1024, 256) == 256
    assert _pick_rows(0, 256) == 0
    assert _pick_rows(257, 256) == 1       # odd rows degrade -> gated out
    assert shape_supported(256) and not shape_supported(100)

    rng = np.random.RandomState(2)
    # odd row count and ineligible hidden both route to the reference
    x = jnp.asarray(rng.randn(257, 128).astype(np.float32))
    out, _ = fused_add_rms_norm(x, x, jnp.ones((128,)), 1e-6, True)
    ref_out, _ = _reference(x, x, jnp.ones((128,)), 1e-6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=1e-6)
    y = jnp.asarray(rng.randn(16, 100).astype(np.float32))
    out2, _ = fused_add_rms_norm(y, y, jnp.ones((100,)), 1e-6, False)
    ref2, _ = _reference(y, y, jnp.ones((100,)), 1e-6)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=1e-6)
    # empty batch: no crash
    e = jnp.zeros((0, 256), jnp.float32)
    out0, _ = fused_add_rms_norm(e, e, jnp.ones((256,)), 1e-6, True)
    assert out0.shape == (0, 256)
