"""Audio IO (reference: python/paddle/audio/backends/ — wave_backend.py).
A pure-stdlib WAV backend (the reference's default backend also falls
back to python `wave` when soundfile is absent)."""
from __future__ import annotations

import wave as _wave
from typing import Optional, Tuple

import numpy as np

from ..tensor import Tensor, to_tensor

__all__ = ["load", "save", "info", "list_available_backends", "get_current_backend", "set_backend"]


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise ValueError("only the stdlib wave_backend ships in this build")


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True) -> Tuple[Tensor, int]:
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
    dt = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dt).reshape(-1, ch)
    if normalize:
        data = data.astype(np.float32) / float(np.iinfo(dt).max)
    arr = data.T if channels_first else data
    return to_tensor(np.ascontiguousarray(arr)), sr


def save(filepath: str, src: Tensor, sample_rate: int,
         channels_first: bool = True, bits_per_sample: int = 16):
    import numpy as np

    data = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T
    if data.dtype.kind == "f":
        data = (np.clip(data, -1, 1) * (2 ** (bits_per_sample - 1) - 1)).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1] if data.ndim > 1 else 1)
        f.setsampwidth(bits_per_sample // 8)
        f.setframerate(sample_rate)
        f.writeframes(data.tobytes())


class AudioInfo:
    def __init__(self, sample_rate, num_frames, num_channels, bits_per_sample):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)
