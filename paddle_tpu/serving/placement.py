"""Placement layer: which ``dp`` replica seats a request.

The cluster-level half of the PR-14 scheduler split (the per-replica half
— pages, slots, queues — is ``serving/admission.py``).  The placement
scheduler never touches pages or slots itself: it ranks replicas by load
and forwards ``submit`` to the chosen replica's own admission path, so
every per-replica invariant (all-or-nothing page reservation, bounded
queues, exact accounting under faults) holds unchanged per replica.

Backpressure composes upward: a replica sheds (typed ``Overloaded``) when
its own bounded queue is full; the placement layer sheds only when EVERY
replica does — one busy replica never rejects work another could absorb.

The default policy is least-loaded with queue depth as the primary
signal: queue depth is the only metric that keeps growing after a replica
saturates (occupancy and active slots clip at capacity), so it is the
gradient that actually spreads a hot spot.  Ties break toward fewer
reserved pages, then fewer active slots, then replica index
(deterministic).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

from .engine import (
    DeadlineExceeded,
    Overloaded,
    Request,
    RequestCancelled,
    RequestState,
)

__all__ = ["LeastLoadedPlacement", "PrefixLocalityPlacement",
           "PlacementScheduler", "replica_load", "replica_role",
           "replica_signals"]


def replica_role(engine) -> str:
    """The replica's disaggregation role ("prefill" | "decode" |
    "colocated" — serving/disagg.py).  Engines built outside a
    :class:`~.disagg.DisaggServingEngine` read as "colocated": they both
    prefill and decode, so every policy treats them as admittable."""
    return getattr(engine, "role", "colocated")


def replica_load(engine) -> Tuple[int, float, int]:
    """One replica's load signal for placement ranking:
    ``(queue_depth, pages_reserved_fraction, active_slots)`` — ordered by
    how discriminating each is past saturation."""
    alloc = engine.allocator
    cap = max(alloc.capacity, 1)
    return (engine.queue.depth, alloc.used_pages / cap,
            engine.scheduler.active_slots)


def replica_signals(engine, adapter: Optional[str] = None
                    ) -> Tuple[bool, float]:
    """The ROADMAP-named per-replica placement signals beyond raw load:
    ``(adapter_resident, spec_acceptance_rate)``.

    - *adapter residency*: whether this replica's LoRA pool already holds
      the tenant's slab.  Adapters register per replica pool, so routing
      a tenant to a non-resident replica FAILS the request at admission
      (typed ``UnknownAdapter``) — residency is close to mandatory, not
      just an affinity win.
    - *speculative acceptance rate*: accepted/proposed draft tokens
      (serving/speculative.py); a replica whose drafts keep being
      accepted produces more tokens per verify dispatch, i.e. has more
      throughput headroom at equal queue depth.  Non-speculative
      replicas read as the neutral 1.0.
    """
    resident = False
    pool = getattr(engine, "lora", None)
    if adapter is not None and pool is not None:
        resident = adapter in pool.adapters()
    totals = getattr(engine, "_spec_totals", None)
    accept = 1.0
    if totals is not None:
        proposed = totals["proposed_tokens"]
        accept = totals["accepted_tokens"] / proposed if proposed else 1.0
    return resident, accept


class LeastLoadedPlacement:
    """Rank replicas least-loaded first (see :func:`replica_load`).

    With the request in hand (``rank_for``), the rank tuple gains the
    per-replica signals of :func:`replica_signals`: a tenant routes to
    the replica where its adapter slab is already seated (residency
    outranks load — a miss is an admission failure, not a slow path),
    and among equally loaded replicas the higher speculative acceptance
    rate wins (more tokens per dispatch).  Without a prompt in hand
    (``rank``) the historical load-only tuple is unchanged."""

    def rank(self, engines: Sequence) -> List[int]:
        return sorted(range(len(engines)),
                      key=lambda i: (replica_load(engines[i]), i))

    def rank_for(self, engines: Sequence, prompt,
                 adapter: Optional[str] = None) -> List[int]:
        def key(i):
            resident, accept = replica_signals(engines[i], adapter)
            depth, frac, active = replica_load(engines[i])
            return (0 if resident else 1, depth, frac, active, -accept, i)

        return sorted(range(len(engines)), key=key)


class PrefixLocalityPlacement(LeastLoadedPlacement):
    """Prefix-locality signal hook: prefer the replica whose prefix cache
    already holds the longest prefix of THIS prompt (per-replica caches
    never share pages, so routing siblings of a prompt family to the same
    replica is what makes their prefixes hit), break ties least-loaded.

    Deliberately a stub-grade heuristic (docs/serving.md "Prefix cache"):
    the lookup is the cache's read-only ``match_len`` walk, load is only
    a tiebreak — a saturated replica with a warm cache still wins over an
    idle cold one.  Production policies would blend match length against
    load; the ``rank_for`` hook is the seam they implement.  Adapter
    residency still outranks the prefix match (a non-resident replica
    cannot serve the tenant at all)."""

    def rank_for(self, engines: Sequence, prompt,
                 adapter: Optional[str] = None) -> List[int]:
        def match(e) -> int:
            cache = getattr(e, "prefix_cache", None)
            return cache.match_len(prompt) if cache is not None else 0

        def key(i):
            resident, accept = replica_signals(engines[i], adapter)
            return (0 if resident else 1, -match(engines[i]),
                    replica_load(engines[i]), -accept, i)

        return sorted(range(len(engines)), key=key)


class PlacementScheduler:
    """Cluster-level request placement over ``dp`` replica engines.

    ``submit`` walks the policy's ranking and seats the request on the
    first replica that accepts it; per-replica ``Overloaded`` (bounded
    queue full) moves on to the next candidate.  Only when EVERY replica
    sheds does the placement layer raise ``Overloaded`` itself — the
    cluster is genuinely out of capacity, not just one replica.

    Validation errors (oversized prompt, bad arguments) are raised by the
    first replica verbatim: they would fail identically everywhere, and
    retrying them across the fleet would just turn one clear error into
    ``dp`` of them.
    """

    def __init__(self, engines: Sequence, policy=None):
        if not engines:
            raise ValueError("PlacementScheduler needs at least one replica")
        self.engines = list(engines)
        self.policy = policy or LeastLoadedPlacement()
        # requests routed per replica (placement observability; the
        # sharded bench prints these as per-replica occupancy companions)
        self.routed = [0] * len(self.engines)
        # cluster-level sheds (every replica backpressured).  Separate
        # from the replicas' own ``shed`` counters so one rejected
        # request is counted ONCE here, not dp times below.
        self.shed_total = 0
        # counter lock: submit() is documented as callable from any
        # thread, and a bare `+=` is the interleaved read-modify-write
        # the PR-9 counter hardening removed from the engine
        self._lock = threading.Lock()
        # re-home parking lot: requests harvested from a draining or dead
        # replica that no survivor could seat RIGHT NOW.  They stay live
        # here (not FAILED) until capacity frees — flush_held() retries
        # them each cluster step, sweep() reaps the ones that cancel or
        # expire while parked (the cross-replica cancel fix: a request
        # held HERE is on no replica's queue, so no replica reaps it).
        self.held: "deque[Request]" = deque()
        self.rehomed_total = 0

    @staticmethod
    def _has_queue_room(engine) -> bool:
        q = engine.queue
        return q.max_depth is None or q.depth < q.max_depth

    @staticmethod
    def _eligible(engine) -> bool:
        """A replica that can accept NEW work: open and not draining."""
        return not (getattr(engine, "draining", False)
                    or getattr(engine, "_closed", False))

    def submit(self, prompt, max_new_tokens: int = 32, **kwargs) -> Request:
        """Place and queue one request; returns the replica's Request.
        Raises typed ``Overloaded`` only when all replicas shed.

        Full replicas are skipped by a queue-room check BEFORE calling
        their ``submit`` — probing a full replica's submit would bump its
        own ``shed`` counter for a request another replica then serves.
        The check races concurrent submitters, so a replica-level
        ``Overloaded`` can still surface; it is caught and the walk moves
        on (that replica's counter recorded a genuine full-queue event).
        """
        last: Optional[Overloaded] = None
        for i in self._order(prompt, kwargs.get("adapter")):
            if not (self._eligible(self.engines[i])
                    and self._has_queue_room(self.engines[i])):
                continue
            try:
                req = self.engines[i].submit(prompt, max_new_tokens,
                                             **kwargs)
            except Overloaded as e:
                last = e
                continue
            with self._lock:
                self.routed[i] += 1
            req.replica = i
            return req
        with self._lock:
            self.shed_total += 1
        raise Overloaded(
            f"all {len(self.engines)} replicas backpressured: "
            "cluster out of queue capacity — back off and retry") from last

    def _order(self, prompt, adapter: Optional[str] = None) -> List[int]:
        # prefix-locality / signals hook: a policy exposing rank_for ranks
        # with the PROMPT (and tenant adapter) in hand; plain policies
        # keep the load-only rank() signature.  Pre-PR-19 policies take
        # rank_for(engines, prompt) only — fall back for them.
        ranker = getattr(self.policy, "rank_for", None)
        if ranker is None:
            return self.policy.rank(self.engines)
        try:
            return ranker(self.engines, prompt, adapter=adapter)
        except TypeError:
            return ranker(self.engines, prompt)

    # -- re-homing (drain / replica loss) ------------------------------

    def resubmit(self, req: Request) -> bool:
        """Re-home one live request harvested off a draining/dead replica.

        Walks the same policy ranking as ``submit`` but seats via
        ``engine.requeue`` — the SAME Request object keeps its id, stream
        callback, ``_done`` event and deadline, which is what makes
        re-homed streams exactly-once.  Returns True when seated; when no
        survivor has room the request parks in ``held`` (still live) and
        False is returned.  Terminal requests (cancelled/expired while in
        flight) are dropped without a walk — sweep() already typed them.
        """
        if req.state not in (RequestState.SUBMITTED,):
            return False
        for i in self._order(req.prompt, req.adapter):
            e = self.engines[i]
            if not (self._eligible(e) and self._has_queue_room(e)):
                continue
            try:
                e.requeue(req)
            except Overloaded:
                continue
            with self._lock:
                self.routed[i] += 1
                self.rehomed_total += 1
            req.replica = i
            req.rehomed += 1
            return True
        with self._lock:
            self.held.append(req)
        return False

    def flush_held(self) -> int:
        """Retry every parked request once, FIFO.  Returns seats found."""
        with self._lock:
            batch = list(self.held)
            self.held.clear()
        seated = 0
        for req in batch:
            if self.resubmit(req):           # re-parks itself on failure
                seated += 1
        return seated

    def sweep(self, now: Optional[float] = None) -> int:
        """Reap held requests that went terminal while parked.

        This is the cross-replica ``cancel()`` fix: a request cancelled
        (or deadline-expired) while held at the placement layer sits on
        no replica's queue, so no replica's ``_reap`` ever observes it —
        without this sweep it would hang its waiter forever.  When NO
        eligible replica remains at all, every held request fails typed
        (capacity is gone for good, not just momentarily).  Returns the
        number of requests reaped.
        """
        now = time.monotonic() if now is None else now
        no_capacity = not any(self._eligible(e) for e in self.engines)
        reaped = 0
        with self._lock:
            keep: "deque[Request]" = deque()
            batch = list(self.held)
            self.held.clear()
        for r in batch:
            if r.cancelled:
                self._terminalize_held(
                    r, RequestState.CANCELLED, RequestCancelled(
                        f"request {r.id} cancelled while held "
                        "for re-homing"))
            elif r.deadline is not None and now >= r.deadline:
                self._terminalize_held(
                    r, RequestState.TIMED_OUT, DeadlineExceeded(
                        f"request {r.id}: deadline_s={r.deadline_s} "
                        "passed while held for re-homing"))
            elif no_capacity:
                self._terminalize_held(
                    r, RequestState.FAILED, Overloaded(
                        f"request {r.id} lost its replica and no "
                        "eligible replica remains to re-home it"))
            else:
                keep.append(r)
                continue
            reaped += 1
        with self._lock:
            self.held.extendleft(reversed(keep))
        return reaped

    @staticmethod
    def _terminalize_held(req: Request, state: str,
                          error: BaseException):
        """Placement-local terminal transition for a held request —
        mirrors the engine's ``_terminalize`` (error, state, terminal
        timestamp, waiter wake-up) without bumping any ONE replica's
        counters for a request that sat on no replica's queue."""
        req.error = error
        req.state = state
        req.t_terminal = time.monotonic()
        req._done.set()

    def pending(self) -> int:
        """Queued + seated requests across every replica, plus requests
        parked in the re-home queue (still live: run_until_idle must not
        declare the cluster idle while they wait for a seat)."""
        return (sum(e.queue.depth + e.scheduler.active_slots
                    for e in self.engines if not getattr(e, "_closed",
                                                         False))
                + len(self.held))

    def loads(self) -> List[Tuple[int, float, int]]:
        return [replica_load(e) for e in self.engines]
