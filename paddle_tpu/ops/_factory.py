"""Op-definition helpers.

TPU-native analog of the reference's YAML op codegen
(reference: paddle/phi/api/yaml/ops.yaml + generator/api_gen.py): instead of
generating C++ from YAML, each op is declared as a pure jax function and these
factories produce the user-facing wrapper (tensor conversion, scalar closure,
autograd capture via dispatch.apply).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..core import op_cache as _op_cache
from ..tensor import Tensor, to_tensor
from . import dispatch

__all__ = ["ensure_tensor", "unary_op", "binary_op", "cmp_op", "logical_op"]

# Python scalars ride along as hashable attrs (part of the op-cache key),
# so `x + 2.0` dispatches a STABLE helper instead of a per-call lambda and
# repeated calls hit the compiled entry.
_SCALARS = (bool, int, float, np.generic)


def ensure_tensor(x, like=None):
    if isinstance(x, Tensor):
        return x
    dtype = None
    if like is not None and isinstance(x, (int, float, bool)) and not isinstance(x, bool):
        dtype = like.dtype
    return to_tensor(x, dtype=dtype)


def unary_op(jfn: Callable, name: str):
    _op_cache.mark_stable(jfn)  # one instance per op definition

    def op(x, name=None):  # noqa: A002  (matches reference signature)
        x = ensure_tensor(x)
        return dispatch.apply(jfn, x, op_name=op.__name__)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise ``{name}`` (TPU-native; see reference ops.yaml entry '{name}')."
    return op


def binary_op(jfn: Callable, name: str):
    _op_cache.mark_stable(jfn)

    def _scalar_rhs(a, *, _scalar):
        return jfn(a, _scalar)

    def _scalar_lhs(b, *, _scalar):
        return jfn(_scalar, b)

    _op_cache.mark_stable(_scalar_rhs)
    _op_cache.mark_stable(_scalar_lhs)

    def op(x, y, name=None):  # noqa: A002
        xt = isinstance(x, Tensor)
        yt = isinstance(y, Tensor)
        if xt and yt:
            return dispatch.apply(jfn, x, y, op_name=op.__name__)
        if xt:
            if isinstance(y, _SCALARS):
                return dispatch.apply(_scalar_rhs, x, op_name=op.__name__,
                                      _scalar=y)
            return dispatch.apply(lambda a: jfn(a, y), x, op_name=op.__name__)
        if yt:
            if isinstance(x, _SCALARS):
                return dispatch.apply(_scalar_lhs, y, op_name=op.__name__,
                                      _scalar=x)
            return dispatch.apply(lambda b: jfn(x, b), y, op_name=op.__name__)
        return dispatch.apply(jfn, ensure_tensor(x), ensure_tensor(y), op_name=op.__name__)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise ``{name}`` with broadcasting."
    return op


def cmp_op(jfn: Callable, name: str):
    _op_cache.mark_stable(jfn)

    def _scalar_rhs(a, *, _scalar):
        return jfn(a, _scalar)

    _scalar_rhs.__name__ = name  # stats bucket matches the op
    _op_cache.mark_stable(_scalar_rhs)

    def op(x, y, name=None):  # noqa: A002
        x = ensure_tensor(x)
        if isinstance(y, Tensor):
            return dispatch.apply_nondiff(jfn, x, y)
        if isinstance(y, _SCALARS):
            return dispatch.apply_nondiff(_scalar_rhs, x, _scalar=y)
        return dispatch.apply_nondiff(lambda a: jfn(a, y), x)

    op.__name__ = name
    return op


def logical_op(jfn: Callable, name: str):
    _op_cache.mark_stable(jfn)

    def op(x, y=None, out=None, name=None):  # noqa: A002
        x = ensure_tensor(x)
        if y is None:
            return dispatch.apply_nondiff(jfn, x)
        y = ensure_tensor(y)
        return dispatch.apply_nondiff(jfn, x, y)

    op.__name__ = name
    return op
