"""Worker script for the multi-process launch test (reference analog:
test/collective/fleet worker scripts run by TestMultipleGpus
start_local_trainers).  Each rank: rendezvous via the native TCPStore,
build a local 4-virtual-device CPU mesh, run a tiny SPMD reduction, then
exchange a tensor cross-rank through the store-backed send/recv."""
import os
import sys

import numpy as np

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import mesh as M


def main():
    env = dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()
    assert world == 2, world

    # local 4-device CPU mesh SPMD sanity (per-host compute)
    import jax

    assert len(jax.devices()) >= 4, jax.devices()
    M.set_mesh(M.build_mesh({"dp": 4}, jax.devices()[:4]))
    x = pt.to_tensor(np.arange(8, dtype=np.float32))
    total = float(pt.ops.sum(x * (rank + 1)))
    assert total == 28.0 * (rank + 1), total

    # cross-host p2p through the job's TCPStore
    from paddle_tpu.distributed.collective import recv, send

    if rank == 0:
        send(pt.to_tensor(np.full((4,), 41.0, np.float32)), dst=1)
        out = pt.to_tensor(np.zeros((2,), np.float32))
        recv(out, src=1)
        assert np.allclose(out.numpy(), 7.0), out.numpy()
    else:
        got = pt.to_tensor(np.zeros((4,), np.float32))
        recv(got, src=0)
        assert np.allclose(got.numpy(), 41.0), got.numpy()
        send(pt.to_tensor(np.full((2,), 7.0, np.float32)), dst=0)

    dist.barrier()
    print(f"WORKER_OK rank={rank}")


if __name__ == "__main__":
    main()
