"""Elastic manager + launcher restart (reference:
fleet/elastic/manager.py:124 heartbeat/TTL membership; launcher
max_restart relaunch)."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.core.native.tcp_store import TCPStore
from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus


def test_membership_and_failure_detection():
    store = TCPStore(host="127.0.0.1", port=0, is_master=True, world_size=2)
    changes = []
    m0 = ElasticManager(store, rank=0, nnodes=2, ttl=1.0, interval=0.2,
                        on_change=lambda alive: changes.append(alive))
    m1 = ElasticManager(store, rank=1, nnodes=2, ttl=1.0, interval=0.2)
    m0.start()
    m1.start()
    time.sleep(0.6)
    assert sorted(m0.alive_nodes()) == [0, 1]
    assert m0.health() == ElasticStatus.COMPLETED
    # node 1 dies (heartbeat stops); TTL expires -> membership change fires.
    # Wait on the CALLBACK (the notification contract), not wall-clock: the
    # detector that observes the change must fire on_change before any
    # caller can see the shrunken membership.
    m1.stop()
    deadline = time.time() + 10
    while time.time() < deadline and not any(a == [0] for a in changes):
        time.sleep(0.2)
    assert any(alive == [0] for alive in changes)
    assert m0.alive_nodes() == [0]
    assert m0.health() in (ElasticStatus.RESTART, ElasticStatus.HOLD)
    m0.stop()


def test_launcher_elastic_restart(tmp_path):
    """A worker that crashes once is relaunched and the job succeeds."""
    script = tmp_path / "flaky.py"
    marker = tmp_path / "crashed_once"
    script.write_text(
        "import os, sys\n"
        f"m = {str(repr(str(marker)))}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(3)\n"
        "print('RECOVERED_OK')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] + ["/root/repo"])
    log_dir = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--elastic_level", "1",
         "--max_restart", "2", "--log_dir", log_dir, str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    logs = "".join(
        open(os.path.join(log_dir, f)).read() for f in os.listdir(log_dir))
    assert "RECOVERED_OK" in logs
    assert "elastic restart 1/2" in proc.stderr


def test_launcher_fail_fast_without_elastic(tmp_path):
    script = tmp_path / "dies.py"
    script.write_text("import sys; sys.exit(5)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] + ["/root/repo"])
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 5
