"""KL divergence registry (reference: python/paddle/distribution/kl.py:37
kl_divergence, :69 register_kl, :103 _dispatch — most-derived match over
registered (type_p, type_q) pairs)."""
from __future__ import annotations

import math

from .. import ops
from .continuous import Beta, Cauchy, Dirichlet, Gumbel, Laplace, LogNormal, Normal, Uniform
from .discrete import Bernoulli, Categorical, Geometric
from .distribution import Distribution

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY: dict = {}


def register_kl(cls_p, cls_q):
    if not (issubclass(cls_p, Distribution) and issubclass(cls_q, Distribution)):
        raise TypeError("cls_p and cls_q must be subclass of Distribution")

    def decorator(f):
        _REGISTRY[(cls_p, cls_q)] = f
        return f

    return decorator


def _dispatch(type_p, type_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(type_p, p) and issubclass(type_q, q)]
    if not matches:
        raise NotImplementedError(
            f"kl_divergence({type_p.__name__}, {type_q.__name__}) is not "
            f"registered")

    # most-derived pair wins (total subclass-depth ordering, reference :106)
    def depth(pair):
        p, q = pair
        return (type_p.__mro__.index(p), type_q.__mro__.index(q))

    return _REGISTRY[min(matches, key=depth)]


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


# -- closed forms (reference kl.py registrations) ---------------------------

@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = ops.square(p.scale / q.scale)
    t1 = ops.square((p.loc - q.loc) / q.scale)
    return 0.5 * (var_ratio + t1 - 1.0 - ops.log(var_ratio))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # support(p) must lie inside support(q); else KL = +inf
    inside = ops.logical_and(q.low <= p.low, p.high <= q.high)
    val = ops.log((q.high - q.low) / (p.high - p.low))
    import numpy as np

    return ops.where(inside, val, ops.full_like(val, np.inf))


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    delta = ops.abs(p.loc - q.loc) / q.scale
    term = scale_ratio * ops.exp(-ops.abs(p.loc - q.loc) / p.scale)
    return -ops.log(scale_ratio) + scale_ratio + delta - 1.0 + (term - scale_ratio)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # KL = log(b2/b1) + γ(b1/b2 − 1) + (μ1−μ2)/b2
    #      + exp((μ2−μ1)/b2)·Γ(1 + b1/b2) − 1
    import jax.numpy as jnp
    import jax.scipy.special as jss

    from ..ops import dispatch as _d

    euler = Gumbel._EULER

    def fn(b1, b2, mu1, mu2):
        ratio = b1 / b2
        return (jnp.log(b2 / b1) + euler * (ratio - 1.0) + (mu1 - mu2) / b2
                + jnp.exp((mu2 - mu1) / b2 + jss.gammaln(1.0 + ratio)) - 1.0)

    return _d.apply(fn, p.scale, q.scale, p.loc, q.loc, op_name="kl_gumbel")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    sp = p.alpha + p.beta
    return ((ops.lgamma(q.alpha) + ops.lgamma(q.beta) - ops.lgamma(q.alpha + q.beta))
            - (ops.lgamma(p.alpha) + ops.lgamma(p.beta) - ops.lgamma(sp))
            + (p.alpha - q.alpha) * ops.digamma(p.alpha)
            + (p.beta - q.beta) * ops.digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta) * ops.digamma(sp))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    cp, cq = p.concentration, q.concentration
    a0 = ops.sum(cp, axis=-1, keepdim=True)
    return (ops.lgamma(ops.sum(cp, axis=-1)) - ops.lgamma(ops.sum(cq, axis=-1))
            - ops.sum(ops.lgamma(cp) - ops.lgamma(cq), axis=-1)
            + ops.sum((cp - cq) * (ops.digamma(cp) - ops.digamma(a0)), axis=-1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    from .discrete import _clip_probs

    pp, qq = _clip_probs(p.probs), _clip_probs(q.probs)
    return (pp * (ops.log(pp) - ops.log(qq))
            + (1.0 - pp) * (ops.log1p(-pp) - ops.log1p(-qq)))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    from ..nn import functional as F

    logp = F.log_softmax(p.logits, axis=-1)
    logq = F.log_softmax(q.logits, axis=-1)
    return ops.sum(ops.exp(logp) * (logp - logq), axis=-1)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    from .discrete import _clip_probs

    pp, qq = _clip_probs(p.probs), _clip_probs(q.probs)
    return (ops.log(pp) - ops.log(qq)
            + (1.0 - pp) / pp * (ops.log1p(-pp) - ops.log1p(-qq)))
