"""Megatron-style tensor-parallel layers.

Reference: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding(:35),
ColumnParallelLinear(:173), RowParallelLinear(:343), ParallelCrossEntropy
(:524); identity/allreduce autograd ops in mp_ops.py.

TPU-native: the SAME math, but partitioning is declared via NamedShardings
on the weights (mp axis) plus sharding constraints on activations; XLA's
SPMD partitioner inserts the all-reduce/all-gather that mp_ops.py codes by
hand. gather_output/input_is_parallel keep their reference meaning as
layout constraints.
"""
from __future__ import annotations

import jax.numpy as jnp

from .... import mesh as _mesh
from ....fleet.base.topology import get_hybrid_communicate_group
from .....nn import functional as F
from .....nn.layer import Layer
from .....ops import dispatch
from .....ops.sharding_ops import shard_constraint, shard_param
from .....tensor import Tensor


def _mp_size():
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    return _mesh.axis_size("mp")


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over the vocab dim on the 'mp' axis
    (reference mp_layers.py:35)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        from ....fleet.base.topology import get_hybrid_communicate_group  # noqa: F811

        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr)
        if _mp_size() > 1:
            shard_param(self.weight, "mp")  # rows sharded across mp

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if _mp_size() > 1:
            out = shard_constraint(out)  # replicated activation (XLA inserts
            # the partial-sum all-reduce over mp from the sharded gather)
        return out


class ColumnParallelLinear(Layer):
    """W sharded by columns over 'mp' (reference mp_layers.py:173)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.bias = (
            self.create_parameter([out_features], is_bias=True)
            if (has_bias or has_bias is None)
            else None
        )
        if _mp_size() > 1:
            shard_param(self.weight, None, "mp")
            if self.bias is not None:
                shard_param(self.bias, "mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if _mp_size() > 1:
            if self.gather_output:
                out = shard_constraint(out)  # all-gather to replicated
            else:
                out = shard_constraint(out, *( [None] * (out.ndim - 1) + ["mp"] ))
        return out


class RowParallelLinear(Layer):
    """W sharded by rows over 'mp'; output partial-sums all-reduced
    (reference mp_layers.py:343)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        if _mp_size() > 1:
            shard_param(self.weight, "mp", None)
            if self.bias is not None:
                shard_param(self.bias)

    def forward(self, x):
        if _mp_size() > 1 and self.input_is_parallel:
            x = shard_constraint(x, *([None] * (x.ndim - 1) + ["mp"]))
        out = F.linear(x, self.weight, self.bias)
        if _mp_size() > 1:
            out = shard_constraint(out)  # forces the mp all-reduce of partials
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over an mp-sharded vocab (reference mp_layers.py:524).
    With GSPMD the logits stay sharded on the class dim; XLA partitions the
    log-softmax reduction with an all-reduce of max/denominator — the same
    algorithm the reference hand-codes."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)
