"""ctc_loss (reference: phi/kernels/cpu/warpctc_kernel.cc via nn/functional
ctc_loss) and flash_attn_unpadded (reference: nn/functional/flash_attention.py
varlen form) — round-5 stub-debt clearance, parity vs torch."""
import numpy as np
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def _ctc_fixture():
    rng = np.random.RandomState(0)
    T, B, C, L = 12, 3, 6, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.int64)
    ilen = np.array([12, 10, 7], np.int64)
    llen = np.array([4, 3, 2], np.int64)
    return logits, labels, ilen, llen


def test_ctc_loss_parity_all_reductions():
    logits, labels, ilen, llen = _ctc_fixture()
    for red in ("none", "sum", "mean"):
        ours = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                          pt.to_tensor(ilen), pt.to_tensor(llen),
                          blank=0, reduction=red).numpy()
        # torch expects log-softmax'd input; the reference warpctc (and we)
        # softmax internally
        ref = TF.ctc_loss(torch.log_softmax(torch.tensor(logits), -1),
                          torch.tensor(labels), torch.tensor(ilen),
                          torch.tensor(llen), blank=0, reduction=red).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5,
                                   atol=1e-6)


def test_ctc_loss_grad_parity():
    logits, labels, ilen, llen = _ctc_fixture()
    t_in = torch.tensor(logits, requires_grad=True)
    TF.ctc_loss(torch.log_softmax(t_in, -1), torch.tensor(labels),
                torch.tensor(ilen), torch.tensor(llen), blank=0,
                reduction="mean").backward()
    x = pt.to_tensor(logits, stop_gradient=False)
    F.ctc_loss(x, pt.to_tensor(labels), pt.to_tensor(ilen),
               pt.to_tensor(llen), blank=0, reduction="mean").backward()
    np.testing.assert_allclose(x.grad.numpy(), t_in.grad.numpy(),
                               rtol=1e-4, atol=1e-6)


def test_ctc_loss_compiled_step():
    logits, labels, ilen, llen = _ctc_fixture()
    x = pt.to_tensor(logits)

    @pt.jit.to_static
    def f(x):
        return F.ctc_loss(x, pt.to_tensor(labels), pt.to_tensor(ilen),
                          pt.to_tensor(llen), reduction="sum")

    eager = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                       pt.to_tensor(ilen), pt.to_tensor(llen),
                       reduction="sum")
    np.testing.assert_allclose(float(f(x)), float(eager), rtol=1e-5)


def test_flash_attn_unpadded_matches_per_sequence_sdpa():
    rng = np.random.RandomState(0)
    H, D = 2, 8
    lens = [5, 3, 7]
    cu = np.cumsum([0] + lens).astype(np.int32)
    total = sum(lens)
    q = rng.randn(total, H, D).astype(np.float32)
    k = rng.randn(total, H, D).astype(np.float32)
    v = rng.randn(total, H, D).astype(np.float32)
    for causal in (False, True):
        out, _ = F.flash_attn_unpadded(
            pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
            pt.to_tensor(cu), pt.to_tensor(cu), causal=causal)
        out = out.numpy()
        for b in range(len(lens)):
            s, e = cu[b], cu[b + 1]
            ref = torch.nn.functional.scaled_dot_product_attention(
                torch.tensor(q[s:e]).transpose(0, 1),
                torch.tensor(k[s:e]).transpose(0, 1),
                torch.tensor(v[s:e]).transpose(0, 1),
                is_causal=causal).transpose(0, 1).numpy()
            np.testing.assert_allclose(out[s:e], ref, rtol=1e-4, atol=2e-6)


def test_ctc_loss_infeasible_is_inf():
    """Input shorter than the label tape needs -> inf (warpctc/torch
    convention), so isinf-based bad-sample filters keep working."""
    logits = np.random.RandomState(1).randn(5, 1, 4).astype(np.float32)
    labels = np.array([[1, 1, 1, 1]], np.int64)  # needs >= 2*4-1=7 frames
    loss = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                      pt.to_tensor(np.array([5], np.int64)),
                      pt.to_tensor(np.array([4], np.int64)),
                      reduction="none").numpy()
    assert np.isinf(loss).all()


def test_flash_attn_unpadded_padded_buffer_zeros():
    """Tokens past cu_seqlens[-1] (padded-buffer varlen layout) must
    produce zero outputs and never be attended to."""
    rng = np.random.RandomState(2)
    H, D = 2, 4
    cu = np.array([0, 3, 5], np.int32)   # 5 real tokens, 3 padding
    q = rng.randn(8, H, D).astype(np.float32)
    k = rng.randn(8, H, D).astype(np.float32)
    v = rng.randn(8, H, D).astype(np.float32)
    out, _ = F.flash_attn_unpadded(
        pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v),
        pt.to_tensor(cu), pt.to_tensor(cu), causal=True)
    out = out.numpy()
    assert np.abs(out[5:]).max() == 0.0
    # real tokens unaffected by the padding rows
    out_nopad, _ = F.flash_attn_unpadded(
        pt.to_tensor(q[:5]), pt.to_tensor(k[:5]), pt.to_tensor(v[:5]),
        pt.to_tensor(cu), pt.to_tensor(cu), causal=True)
    np.testing.assert_allclose(out[:5], out_nopad.numpy(), rtol=1e-5,
                               atol=1e-7)
