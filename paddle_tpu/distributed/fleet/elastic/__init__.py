"""Elastic training: membership, failure detection, restart.

Reference: python/paddle/distributed/fleet/elastic/manager.py:124
(ElasticManager: ranks register under an etcd prefix with TTL leases,
heartbeat thread :254-268, watch membership host_call_back:240; the
launcher relaunches workers with a rescaled spec on change, bounded by
--max_restart).

TPU-native redesign: the KV substrate is the job's native TCPStore (no
etcd in the image).  Each node heartbeats by INCREMENTING a store-side
counter ``elastic/beat/<rank>`` — liveness is "the counter moved within
the last TTL seconds of the WATCHER's clock", so detection never
compares wall clocks across hosts (cross-host clock skew > TTL would
otherwise mark healthy nodes dead).  On membership change the manager
invokes the restart callback (the launcher's relaunch path) — the same
contract the reference's ElasticManager has with
launch/controllers/master.py.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus", "ElasticRunResult",
           "run_elastic"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, store, rank: int, nnodes: int,
                 min_nodes: Optional[int] = None,
                 max_nodes: Optional[int] = None,
                 ttl: float = 10.0, interval: float = 2.0,
                 on_change: Optional[Callable[[List[int]], None]] = None):
        self._store = store
        self._rank = rank
        self._nnodes = nnodes
        self._min = min_nodes or nnodes
        self._max = max_nodes or nnodes
        self._ttl = ttl
        self._interval = interval
        self._on_change = on_change
        self._stop = threading.Event()
        # rank -> (last counter value seen, local monotonic time it changed)
        # _seen and the last-computed membership are shared between user
        # calls to alive_nodes() and the watch thread; ALL detection state
        # is guarded by _lock and on_change fires from the DETECTION SITE
        # (single-detector contract — round-3 race: the user's poll flipped
        # a node to dead and the watch thread's next-tick comparison fired
        # the callback only after the caller had already observed the
        # change).
        # Two-lock notification design.  _lock guards detection state
        # (_seen) and stamps each computed membership with a sequence
        # number; _notify_lock serializes callback delivery and keeps it
        # ordered via the sequence (a stale racer is skipped, so callbacks
        # can never be delivered out of order).  The callback itself runs
        # holding only _notify_lock — NOT _lock — so user code inside
        # on_change may take its own locks and call alive_nodes()/health()
        # without a cross-lock deadlock.  On callback failure the
        # last-notified membership is left unchanged so the next detection
        # re-fires.
        self._seen: Dict[int, tuple] = {}
        # ranks currently considered dead (for missed-beat telemetry:
        # count alive->dead TRANSITIONS, not every stale poll)
        self._dead: set = set()
        # test-only fault injection at the 'heartbeat' point
        # (paddle_tpu.faults.FaultInjector.install(manager))
        self._fault_hook = None
        self._lock = threading.Lock()
        # RLock: an on_change callback may itself call alive_nodes()/
        # health() (re-entering _deliver on the same thread) without
        # deadlocking; cross-thread ordering is still serialized
        self._notify_lock = threading.RLock()
        self._seq = 0
        self._notified_seq = 0
        self._notified_set: Optional[frozenset] = None
        self._threads: List[threading.Thread] = []
        self.enabled = True

    # -- identity / tuning surface (the failure-detector contract the
    # fault-tolerance layer consumes) ------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def ttl(self) -> float:
        """Liveness window: a rank whose beat counter hasn't moved for
        ttl seconds of THIS watcher's clock is dead."""
        return self._ttl

    @property
    def min_nodes(self) -> int:
        return self._min

    def has_registered(self, rank: int) -> bool:
        """True once ``rank`` has EVER heartbeated (its beat key exists).
        Distinguishes a dead rank (key present, counter stale) from one
        still booting (no key yet) — the fault-tolerance waits only
        declare PeerLostError for the former."""
        try:
            return bool(self._store.check(f"elastic/beat/{int(rank)}"))
        except Exception:  # noqa: BLE001 — store outage: don't condemn
            return False

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Register + start the heartbeat and watch threads (reference
        manager.py heartbeat thread :254).  Also registers this manager
        as the process's failure detector, making every store-backed
        collective wait peer-loss-aware (docs/distributed_faults.md)."""
        self._beat()
        from ... import fault_tolerance as _ft

        _ft.set_failure_detector(self)
        t1 = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t2 = threading.Thread(target=self._watch_loop, daemon=True)
        t1.start()
        t2.start()
        self._threads = [t1, t2]

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self._interval * 2)
        from ... import fault_tolerance as _ft

        _ft.clear_failure_detector(self)

    exit = stop

    # -- heartbeat -------------------------------------------------------
    def _beat(self):
        ctx = {"rank": self._rank, "skip": False}
        if self._fault_hook is not None:
            self._fault_hook("heartbeat", ctx)
        if ctx.get("skip"):
            return  # injected missed beat (peers will see us as dying)
        self._store.add(f"elastic/beat/{self._rank}", 1)

    def _heartbeat_loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except Exception:
                pass  # transient store outage: next beat retries

    # -- watch -----------------------------------------------------------
    def alive_nodes(self) -> List[int]:
        """Compute current membership; if it CHANGED since the last
        computation (by any caller), fire on_change before returning —
        whoever detects, notifies, so a user poll can never observe a
        membership the callback hasn't been told about."""
        with self._lock:
            now = time.monotonic()
            alive = []
            for r in range(self._max):
                key = f"elastic/beat/{r}"
                try:
                    if not self._store.check(key):
                        continue
                    # add(key, 0) reads the counter without bumping it
                    ctr = self._store.add(key, 0)
                except Exception:
                    continue
                last = self._seen.get(r)
                if last is None or last[0] != ctr:
                    self._seen[r] = (ctr, now)
                    self._dead.discard(r)
                    alive.append(r)
                elif now - last[1] <= self._ttl:
                    alive.append(r)
                elif r not in self._dead:
                    # alive -> dead transition: missed-beat telemetry
                    self._dead.add(r)
                    try:
                        from ....telemetry.metrics import registry

                        registry().counter(
                            "dist_missed_beat_total",
                            help="ranks whose heartbeat went stale past TTL",
                        ).inc(rank=str(r))
                    except Exception:  # noqa: BLE001 — telemetry best-effort
                        pass
            cur = frozenset(alive)
            self._seq += 1
            seq = self._seq
        self._deliver(cur, seq)
        return alive

    def _deliver(self, cur: frozenset, seq: int):
        with self._notify_lock:
            if seq <= self._notified_seq:
                return  # a newer detection already delivered
            self._notified_seq = seq
            prev = self._notified_set
            if prev is None:
                # very first computation: record silently.  prev may later
                # be the EMPTY set (total store outage) — recovery from
                # that IS a change and notifies.
                self._notified_set = cur
                return
            if cur == prev or self._on_change is None:
                self._notified_set = cur
                return
            try:
                self._on_change(sorted(cur))
                self._notified_set = cur
            except Exception as e:
                # leave _notified_set at prev so the next detection
                # re-fires — a transient callback failure must not
                # permanently swallow the membership change (nor propagate
                # into user calls of alive_nodes()/health()/wait())
                import sys
                sys.stderr.write(
                    f"[paddle_tpu.elastic] on_change failed: {e!r}; "
                    "will retry on next detection\n")

    def _watch_loop(self):
        # periodic detection only: notification lives in alive_nodes()
        while not self._stop.wait(self._interval):
            try:
                self.alive_nodes()
            except Exception:
                continue

    # -- checkpoint/restart integration ---------------------------------
    def chain_on_change(self, callback: Callable[[List[int]], None]):
        """Append ``callback`` to the membership-change notification (the
        restart contract): existing on_change fires first, then the new
        one.  This is how a checkpoint.PreemptionHandler plugs in —
        ``mgr.chain_on_change(handler.as_elastic_on_change())`` makes any
        membership change request checkpoint-then-clean-exit at the next
        step boundary.  Callbacks registered here run under the same
        delivery serialization (and retry-on-failure) as the original."""
        with self._notify_lock:
            prev = self._on_change

            def chained(membership):
                if prev is not None:
                    prev(membership)
                callback(membership)

            self._on_change = chained

    # -- reference-API surface ------------------------------------------
    def health(self) -> str:
        n = len(self.alive_nodes())
        if n >= self._nnodes:
            return ElasticStatus.COMPLETED
        if n >= self._min:
            return ElasticStatus.RESTART  # shrink within [min, max]
        return ElasticStatus.HOLD  # wait for nodes to come back

    def wait(self, timeout: float = 300.0) -> bool:
        """Block until at least min nodes are alive (rescaled bring-up).
        Monotonic deadline: a wall-clock jump must not expire it."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.alive_nodes()) >= self._min:
                return True
            time.sleep(self._interval)
        return False


from .run import ElasticRunResult, run_elastic  # noqa: E402,F401
