"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).
Attention rides scaled_dot_product_attention (XLA-fused / Pallas flash)."""
from __future__ import annotations

import jax.numpy as jnp

from ... import ops
from ...tensor import Tensor
from .. import functional as F
from ..layer import Layer
from .common import Dropout, Linear
from .container import LayerList
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype == "bool":
        return attn_mask
    return attn_mask


class MultiHeadAttention(Layer):
    """Reference nn/layer/transformer.py MultiHeadAttention. q/k/v projections
    are single matmuls; the attention core is the fused SDPA."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b = query.shape[0]
        q = self.q_proj(query).reshape([b, -1, self.num_heads, self.head_dim])
        k = self.k_proj(key).reshape([b, -1, self.num_heads, self.head_dim])
        v = self.v_proj(value).reshape([b, -1, self.num_heads, self.head_dim])

        if cache is not None:
            k = ops.concat([cache.k, k], axis=1)
            v = ops.concat([cache.v, v], axis=1)
            cache = type(cache)(k, v)

        mask = None
        if attn_mask is not None:
            mask = attn_mask  # [b, h, q, k] or broadcastable additive/bool
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            is_causal=False, training=self.training,
        )
        out = out.reshape([b, -1, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        b = key.shape[0]
        k = ops.zeros([b, 0, self.num_heads, self.head_dim], key.dtype.name)
        v = ops.zeros([b, 0, self.num_heads, self.head_dim], key.dtype.name)
        return MultiHeadAttention.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead,
            dropout if attn_dropout is None else attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(dropout if act_dropout is None else act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer) for i in range(num_layers)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask)
            else:
                out, c = layer(out, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout if attn_dropout is None else attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.cross_attn = MultiHeadAttention(
            d_model, nhead, dropout if attn_dropout is None else attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(dropout if act_dropout is None else act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer) for i in range(num_layers)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None,
                 act_dropout=None, normalize_before=False, weight_attr=None,
                 bias_attr=None, custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            self.encoder = TransformerEncoder(
                enc, num_encoder_layers, LayerNorm(d_model) if normalize_before else None
            )
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            self.decoder = TransformerDecoder(
                dec, num_decoder_layers, LayerNorm(d_model) if normalize_before else None
            )
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ... import ops as _ops

        m = jnp.triu(jnp.full((length, length), float("-inf")), k=1)
        return Tensor(m)
