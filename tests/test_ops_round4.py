"""Round-4 op batch: diag_embed/fill_diagonal/gather_tree, huber/log loss,
grid_sample/affine_grid/channel_shuffle, exponential_, generalized
interpolate (3/4/5-D, align_corners) — numeric parity vs torch where
torch has the op (CPU reference), else vs closed form."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def test_diag_embed_and_fill_diagonal():
    v = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    out = pt.ops.diag_embed(pt.to_tensor(v)).numpy()
    ref = torch.diag_embed(torch.tensor(v)).numpy()
    np.testing.assert_allclose(out, ref)
    out_off = pt.ops.diag_embed(pt.to_tensor(v), offset=1).numpy()
    ref_off = torch.diag_embed(torch.tensor(v), offset=1).numpy()
    np.testing.assert_allclose(out_off, ref_off)

    x = pt.to_tensor(np.zeros((4, 4), np.float32))
    pt.ops.fill_diagonal_(x, 7.0)
    np.testing.assert_allclose(np.diag(x.numpy()), [7.0] * 4)


def test_fill_diagonal_tensor():
    x = np.zeros((3, 3), np.float32)
    y = np.array([1.0, 2.0, 3.0], np.float32)
    out = pt.ops.fill_diagonal_tensor(pt.to_tensor(x), pt.to_tensor(y)).numpy()
    np.testing.assert_allclose(np.diag(out), y)
    assert out.sum() == y.sum()


def test_gather_tree_backtrace():
    # T=3, B=1, W=2 beams; beam1 at t=2 points at parent 1->0 chain
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
    par = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
    out = pt.ops.gather_tree(pt.to_tensor(ids), pt.to_tensor(par)).numpy()
    np.testing.assert_array_equal(out, [[[1, 1]], [[4, 3]], [[5, 6]]])


def test_huber_and_log_loss():
    a = np.array([0.5, 2.0], np.float32)
    b = np.zeros(2, np.float32)
    ours = float(F.huber_loss(pt.to_tensor(a), pt.to_tensor(b),
                              delta=1.0, reduction="sum"))
    ref = float(TF.huber_loss(torch.tensor(a), torch.tensor(b),
                              reduction="sum", delta=1.0))
    np.testing.assert_allclose(ours, ref, rtol=1e-6)

    p = np.array([0.9, 0.2], np.float32)
    y = np.array([1.0, 0.0], np.float32)
    out = F.log_loss(pt.to_tensor(p), pt.to_tensor(y), epsilon=1e-4).numpy()
    want = -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_grid_sample_matches_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 7).astype(np.float32)
    theta = np.tile(np.array([[[0.8, 0.1, 0.1], [-0.2, 0.9, -0.1]]],
                             np.float32), (2, 1, 1))
    for ac in (True, False):
        grid_t = TF.affine_grid(torch.tensor(theta), (2, 3, 6, 8),
                                align_corners=ac)
        grid_o = F.affine_grid(pt.to_tensor(theta), [2, 3, 6, 8],
                               align_corners=ac)
        np.testing.assert_allclose(grid_o.numpy(), grid_t.numpy(),
                                   rtol=1e-5, atol=1e-6)
        ref = TF.grid_sample(torch.tensor(x), grid_t, mode="bilinear",
                             padding_mode="zeros", align_corners=ac)
        ours = F.grid_sample(pt.to_tensor(x), grid_o, mode="bilinear",
                             padding_mode="zeros", align_corners=ac)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_channel_shuffle_matches_torch():
    x = np.arange(2 * 6 * 2 * 2, dtype=np.float32).reshape(2, 6, 2, 2)
    ours = F.channel_shuffle(pt.to_tensor(x), 3).numpy()
    ref = torch.channel_shuffle(torch.tensor(x), 3).numpy()
    np.testing.assert_allclose(ours, ref)


def test_fold_unfold_roundtrip_matches_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 6, 8).astype(np.float32)
    cols = F.unfold(pt.to_tensor(x), [2, 2], strides=2)
    ref_cols = TF.unfold(torch.tensor(x), (2, 2), stride=2).numpy()
    np.testing.assert_allclose(cols.numpy(), ref_cols, rtol=1e-6)
    back = F.fold(cols, [6, 8], [2, 2], strides=2)
    ref_back = TF.fold(torch.tensor(ref_cols), (6, 8), (2, 2),
                       stride=2).numpy()
    np.testing.assert_allclose(back.numpy(), ref_back, rtol=1e-6)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)  # stride=kernel


def test_fill_and_zero_inplace():
    x = pt.to_tensor(np.ones((2, 3), np.float32))
    pt.ops.fill_(x, 4.0)
    np.testing.assert_allclose(x.numpy(), np.full((2, 3), 4.0))
    pt.ops.zero_(x)
    np.testing.assert_allclose(x.numpy(), np.zeros((2, 3)))


def test_exponential_inplace():
    pt.seed(0)
    x = pt.to_tensor(np.zeros(5000, np.float32))
    pt.ops.exponential_(x, lam=2.0)
    m = float(x.numpy().mean())
    assert abs(m - 0.5) < 0.05  # E[Exp(2)] = 0.5
    assert (x.numpy() >= 0).all()


@pytest.mark.parametrize("ac", [False, True])
def test_interpolate_parity_3d_4d_5d(ac):
    rng = np.random.RandomState(1)
    x3 = rng.randn(2, 3, 9).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x3), size=[5], mode="linear",
                         align_corners=ac, data_format="NCW").numpy()
    ref = TF.interpolate(torch.tensor(x3), size=(5,), mode="linear",
                         align_corners=ac).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    x4 = rng.randn(2, 3, 5, 7).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x4), size=[10, 14], mode="bilinear",
                         align_corners=ac).numpy()
    ref = TF.interpolate(torch.tensor(x4), size=(10, 14), mode="bilinear",
                         align_corners=ac).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    x5 = rng.randn(1, 2, 4, 5, 6).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x5), size=[8, 10, 3],
                         mode="trilinear", align_corners=ac,
                         data_format="NCDHW").numpy()
    ref = TF.interpolate(torch.tensor(x5), size=(8, 10, 3),
                         mode="trilinear", align_corners=ac).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_interpolate_nearest_floor_convention():
    """Round-5 advisor fix: nearest with align_corners=False must use the
    legacy floor(i * in/out) convention (paddle default align_mode=0 ==
    torch 'nearest'), which differs from half-pixel round() for
    non-integer scale factors."""
    rng = np.random.RandomState(3)
    x4 = rng.randn(2, 3, 5, 7).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x4), size=[8, 11],
                         mode="nearest").numpy()
    ref = TF.interpolate(torch.tensor(x4), size=(8, 11),
                         mode="nearest").numpy()
    np.testing.assert_allclose(ours, ref)

    x3 = rng.randn(2, 3, 9).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x3), scale_factor=1.7,
                         mode="nearest", data_format="NCW").numpy()
    ref = TF.interpolate(torch.tensor(x3), scale_factor=1.7,
                         mode="nearest").numpy()
    np.testing.assert_allclose(ours, ref)


def test_interpolate_linear_explicit_scale_ratio():
    """Linear family must also use ratio=1/scale when an explicit
    scale_factor is given (reference kernels), not the in/out size ratio
    the rounded output size implies."""
    rng = np.random.RandomState(5)
    x4 = rng.randn(1, 2, 9, 9).astype(np.float32)
    ours = F.interpolate(pt.to_tensor(x4), scale_factor=1.7,
                         mode="bilinear").numpy()
    ref = TF.interpolate(torch.tensor(x4), scale_factor=1.7,
                         mode="bilinear").numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_bicubic_parity_both_align_modes():
    """Keys cubic with a=-0.75 (the reference/torch kernel; jax.image's
    cubic uses a=-0.5 and was replaced)."""
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 7, 9).astype(np.float32)
    for ac in (False, True):
        ours = F.interpolate(pt.to_tensor(x), size=[12, 5],
                             mode="bicubic", align_corners=ac).numpy()
        ref = TF.interpolate(torch.tensor(x), size=(12, 5),
                             mode="bicubic", align_corners=ac).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
    ours = F.interpolate(pt.to_tensor(x), scale_factor=1.7,
                         mode="bicubic").numpy()
    ref = TF.interpolate(torch.tensor(x), scale_factor=1.7,
                         mode="bicubic").numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


def test_adaptive_pool_nhwc_and_mask():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 7, 9).astype(np.float32)
    o_nhwc = F.adaptive_avg_pool2d(
        pt.to_tensor(np.transpose(x, (0, 2, 3, 1))), 3,
        data_format="NHWC").numpy()
    o_nchw = F.adaptive_avg_pool2d(pt.to_tensor(x), 3).numpy()
    np.testing.assert_allclose(np.transpose(o_nhwc, (0, 3, 1, 2)),
                               o_nchw, atol=1e-6)
    ours, idx = F.adaptive_max_pool2d(pt.to_tensor(x), 3,
                                      return_mask=True)
    ref, ridx = TF.adaptive_max_pool2d(torch.tensor(x), 3,
                                       return_indices=True)
    np.testing.assert_allclose(ours.numpy(), ref.numpy(), atol=1e-6)
    np.testing.assert_array_equal(idx.numpy(), ridx.numpy())
