"""Discrete Fourier transforms (reference: python/paddle/fft.py, e.g.
``fft`` at :167 → fft_c2c; the reference lowers to cuFFT/mkl kernels at
paddle/phi/kernels/funcs/fft.h).

TPU-native: every transform is a differentiable jnp.fft lowering dispatched
through the eager tape — jax's FFT VJPs replace the reference's handwritten
fft_grad kernels, and under ``jit.to_static`` they fuse into the XLA
program.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .ops import dispatch
from .ops._factory import ensure_tensor
from .tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', 'backward' "
            f"or 'ortho'")
    return norm


def _apply1(fn_name, x, n, axis, norm, *, op_name, to_complex=False):
    _check_norm(norm)
    x = ensure_tensor(x)
    raw_fn = getattr(jnp.fft, fn_name)

    def fn(a):
        if to_complex and not jnp.iscomplexobj(a):
            a = a.astype(jnp.complex64 if a.dtype != jnp.float64 else jnp.complex128)
        return raw_fn(a, n=n, axis=axis, norm=norm)

    return dispatch.apply(fn, x, op_name=op_name)


def _applyn(fn_name, x, s, axes, norm, *, op_name, to_complex=False):
    _check_norm(norm)
    x = ensure_tensor(x)
    raw_fn = getattr(jnp.fft, fn_name)

    def fn(a):
        if to_complex and not jnp.iscomplexobj(a):
            a = a.astype(jnp.complex64 if a.dtype != jnp.float64 else jnp.complex128)
        return raw_fn(a, s=s, axes=axes, norm=norm)

    return dispatch.apply(fn, x, op_name=op_name)


# -- 1-D ---------------------------------------------------------------------

def fft(x, n=None, axis=-1, norm="backward", name=None):
    """Complex-to-complex 1-D DFT (reference python/paddle/fft.py:167)."""
    return _apply1("fft", x, n, axis, norm, op_name="fft", to_complex=True)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _apply1("ifft", x, n, axis, norm, op_name="ifft", to_complex=True)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _apply1("rfft", x, n, axis, norm, op_name="rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _apply1("irfft", x, n, axis, norm, op_name="irfft", to_complex=True)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _apply1("hfft", x, n, axis, norm, op_name="hfft", to_complex=True)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _apply1("ihfft", x, n, axis, norm, op_name="ihfft")


# -- 2-D ---------------------------------------------------------------------

def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _applyn("fft2", x, s, axes, norm, op_name="fft2", to_complex=True)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _applyn("ifft2", x, s, axes, norm, op_name="ifft2", to_complex=True)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _applyn("rfft2", x, s, axes, norm, op_name="rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _applyn("irfft2", x, s, axes, norm, op_name="irfft2", to_complex=True)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    x = ensure_tensor(x)

    def fn(a):
        if not jnp.iscomplexobj(a):
            a = a.astype(jnp.complex64 if a.dtype != jnp.float64 else jnp.complex128)
        # hfft over the last axis of `axes`, plain ifft over the rest
        a = jnp.fft.ifftn(a, s=None if s is None else s[:-1], axes=axes[:-1],
                          norm=norm)
        n_last = None if s is None else s[-1]
        return jnp.fft.hfft(a, n=n_last, axis=axes[-1], norm=norm)

    return dispatch.apply(fn, x, op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_norm(norm)
    x = ensure_tensor(x)

    def fn(a):
        n_last = None if s is None else s[-1]
        a = jnp.fft.ihfft(a, n=n_last, axis=axes[-1], norm=norm)
        return jnp.fft.fftn(a, s=None if s is None else s[:-1], axes=axes[:-1],
                            norm=norm)

    return dispatch.apply(fn, x, op_name="ihfft2")


# -- N-D ---------------------------------------------------------------------

def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _applyn("fftn", x, s, axes, norm, op_name="fftn", to_complex=True)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _applyn("ifftn", x, s, axes, norm, op_name="ifftn", to_complex=True)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _applyn("rfftn", x, s, axes, norm, op_name="rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _applyn("irfftn", x, s, axes, norm, op_name="irfftn", to_complex=True)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    ax = tuple(range(nd)) if axes is None else tuple(axes)
    return hfft2(x, s, ax, norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    ax = tuple(range(nd)) if axes is None else tuple(axes)
    return ihfft2(x, s, ax, norm)


# -- helpers -----------------------------------------------------------------

def fftfreq(n, d=1.0, dtype=None, name=None):
    """Sample frequencies for fft output bins (reference fft.py:1236)."""
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .core.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out, stop_gradient=True)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .core.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out, stop_gradient=True)


def fftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                          op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                          op_name="ifftshift")
