"""Switch (top-1) gate (reference gate/switch_gate.py): logits are
multiplicatively jittered by U(1-eps, 1+eps) during training."""
from __future__ import annotations

from .naive_gate import NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, inp):
        logits = super().forward(inp)
        if self.training and self.switch_eps > 0:
            from ......ops import random as _random

            noise = _random.uniform(
                logits.shape, dtype="float32",
                min=1.0 - self.switch_eps, max=1.0 + self.switch_eps)
            logits = logits * noise
        return logits
