"""Graph Lint: one positive and one negative case per pass (GL001-GL007),
baseline suppression round-trip, the jit.to_static compile hook, the
kernel-gate GL002 reasons, op-cache shape-key counters, and the CLI exit
codes (0 clean / 1 new findings / 2 internal error)."""
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis
from paddle_tpu.analysis import Baseline, LintConfig

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(report):
    return [f.code for f in report.findings]


def _s(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# GL001 dtype-promotion
# ---------------------------------------------------------------------------

def test_gl001_upcast_feeding_dot_flagged():
    def fn(x, w):
        return x.astype(jnp.float32) @ w

    rep = analysis.lint(fn, _s((64, 64), jnp.bfloat16),
                        _s((64, 64), jnp.float32))
    hits = [f for f in rep.findings if f.code == "GL001"]
    assert hits and hits[0].severity == "error"
    assert "dot_general" in hits[0].primitive
    assert hits[0].provenance  # eqn provenance is attached


def test_gl001_mixed_dtype_dot_flagged():
    def fn(x, w):
        return jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    rep = analysis.lint(fn, _s((32, 32), jnp.float32),
                        _s((32, 32), jnp.bfloat16))
    assert any(f.code == "GL001" and "mixed" in f.detail for f in rep)


def test_gl001_pure_bf16_dot_clean():
    def fn(x, w):
        return x @ w

    rep = analysis.lint(fn, _s((64, 64), jnp.bfloat16),
                        _s((64, 64), jnp.bfloat16))
    assert "GL001" not in _codes(rep)


def test_gl001_intentional_fp32_softmax_not_flagged():
    # upcasting for VPU math (softmax/norm) is fine — only dots count
    def fn(x):
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)

    rep = analysis.lint(fn, _s((32, 32), jnp.bfloat16))
    assert "GL001" not in _codes(rep)


def test_gl001_x64_leak_flagged():
    def fn(x):
        return x.astype(jnp.float64) * 2.0

    rep = analysis.lint(fn, _s((8,), jnp.float32))
    assert any(f.code == "GL001" and "x64" in f.detail for f in rep)


# ---------------------------------------------------------------------------
# GL002 tile-misalignment
# ---------------------------------------------------------------------------

def test_gl002_misaligned_dot_flagged():
    def fn(x, w):
        return x @ w

    rep = analysis.lint(fn, _s((512, 1000)), _s((1000, 256)),
                        config=LintConfig(tile_min_bytes=1024))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "1000" in hits[0].message


def test_gl002_aligned_dot_clean():
    def fn(x, w):
        return x @ w

    rep = analysis.lint(fn, _s((512, 1024)), _s((1024, 256)),
                        config=LintConfig(tile_min_bytes=1024))
    assert "GL002" not in _codes(rep)


def test_gl002_small_operands_ignored():
    # dims at/below one tile pad once — not actionable, not flagged
    def fn(x, w):
        return x @ w

    rep = analysis.lint(fn, _s((8, 64)), _s((64, 100)))
    assert "GL002" not in _codes(rep)


def test_gl002_matches_kernel_gate_rules():
    """The linter and the Pallas eligibility gates share one rule set."""
    from paddle_tpu.ops.pallas_kernels.decode_attention import (
        decode_shape_supported, decode_shape_unsupported_reason,
    )
    from paddle_tpu.ops.pallas_kernels.flash_attention import (
        shape_supported, shape_unsupported_reason,
    )

    assert shape_supported(512, 64) and shape_unsupported_reason(512, 64) is None
    r = shape_unsupported_reason(100, 48)
    assert not shape_supported(100, 48)
    assert r.code == "GL002" and "seq_len=100" in str(r) and "head_dim=48" in str(r)

    assert decode_shape_supported(128, 64)
    r = decode_shape_unsupported_reason(96, 64)
    assert not decode_shape_supported(96, 64)
    assert r.code == "GL002" and r.kernel == "decode_attention"


# ---------------------------------------------------------------------------
# GL003 host-sync
# ---------------------------------------------------------------------------

def test_gl003_callback_flagged():
    def fn(x):
        jax.debug.print("x sum {s}", s=x.sum())
        return x * 2

    rep = analysis.lint(fn, _s((8,)))
    assert any(f.code == "GL003" for f in rep)


def test_gl003_pure_compute_clean():
    def fn(x):
        return x * 2

    rep = analysis.lint(fn, _s((8,)))
    assert "GL003" not in _codes(rep)


# ---------------------------------------------------------------------------
# GL004 donation-miss
# ---------------------------------------------------------------------------

_DON_CFG = LintConfig(donation_min_bytes=4096)


def _cache_step(cache, x):
    return cache.at[0].set(x), x.sum()


def test_gl004_undonated_large_buffer_flagged():
    rep = analysis.lint(_cache_step, _s((64, 64)), _s((64,)),
                        config=_DON_CFG)
    hits = [f for f in rep.findings if f.code == "GL004"]
    assert hits and "input 0" in hits[0].message


def test_gl004_donated_buffer_clean():
    rep = analysis.lint(_cache_step, _s((64, 64)), _s((64,)),
                        config=_DON_CFG, donate_argnums=(0,))
    assert "GL004" not in _codes(rep)


def test_gl004_passthrough_input_not_flagged():
    # an input returned unchanged is alive — donating it would be wrong
    def fn(big, x):
        return big, big.sum() + x

    rep = analysis.lint(fn, _s((64, 64)), _s(()), config=_DON_CFG)
    assert "GL004" not in _codes(rep)


# ---------------------------------------------------------------------------
# GL005 dead-code
# ---------------------------------------------------------------------------

def test_gl005_dead_eqn_flagged():
    def fn(x):
        _wasted = x @ x.T  # traced, never used
        return x + 1

    rep = analysis.lint(fn, _s((16, 16)))
    assert any(f.code == "GL005" for f in rep)


def test_gl005_live_graph_clean():
    def fn(x):
        y = x @ x.T
        return x + y.sum()

    rep = analysis.lint(fn, _s((16, 16)))
    assert "GL005" not in _codes(rep)


def test_gl005_effectful_eqn_not_dead():
    def fn(x):
        jax.debug.print("{s}", s=x.sum())  # unused result, but effectful
        return x + 1

    rep = analysis.lint(fn, _s((8,)))
    assert "GL005" not in _codes(rep)


# ---------------------------------------------------------------------------
# GL006 intermediate-blowup
# ---------------------------------------------------------------------------

_BLOW_CFG = LintConfig(blowup_min_bytes=4096, blowup_ratio=4.0)


def test_gl006_broadcast_blowup_flagged():
    def fn(x):
        return jnp.broadcast_to(x[:, None], (128, 4096)) * 1.0

    rep = analysis.lint(fn, _s((128,)), config=_BLOW_CFG)
    assert any(f.code == "GL006" for f in rep)


def test_gl006_proportionate_output_clean():
    def fn(x):
        return jnp.concatenate([x, x], axis=0)  # 2x < ratio 4x

    rep = analysis.lint(fn, _s((128, 128)), config=_BLOW_CFG)
    assert "GL006" not in _codes(rep)


# ---------------------------------------------------------------------------
# GL007 retrace-churn (runtime counters)
# ---------------------------------------------------------------------------

def test_gl007_shape_churn_flagged():
    cfg = LintConfig(churn_shape_keys=4)
    rep = analysis.churn_findings(
        cfg, op_stats={"matmul": {"shape_keys": 9}},
        static_fns={}, trace_counts={})
    assert any(f.code == "GL007" and "matmul" in f.message for f in rep)


def test_gl007_decode_retrace_flagged():
    cfg = LintConfig(churn_max_decode_traces=6)
    rep = analysis.churn_findings(
        cfg, op_stats={}, static_fns={}, trace_counts={"decode": 40})
    assert any(f.code == "GL007" and "decode" in f.message for f in rep)


def test_gl007_quiet_counters_clean():
    rep = analysis.churn_findings(
        op_stats={"matmul": {"shape_keys": 3}},
        static_fns={"train_step": 1},
        trace_counts={"prefill": 2, "decode": 2})
    assert len(rep) == 0


def test_gl007_trace_limit_scales_with_compiled_programs():
    """Trace counts are process-global; N legitimately cached engines pay
    N compiles' worth of traces — that must NOT read as churn."""
    cfg = LintConfig(churn_max_decode_traces=6)
    # 4 engines x 2 traces each = 8 > 6, but 4 compiled programs are known
    rep = analysis.churn_findings(
        cfg, op_stats={}, static_fns={}, trace_counts={"decode": 8},
        program_counts={"decode": 4})
    assert len(rep) == 0
    # the same count against ONE program is genuine churn
    rep = analysis.churn_findings(
        cfg, op_stats={}, static_fns={}, trace_counts={"decode": 8},
        program_counts={"decode": 1})
    assert any(f.code == "GL007" for f in rep)


def test_op_cache_stats_export_shape_keys():
    """core/op_cache.stats() exposes per-op distinct shape-key counts
    (the GL007 feed) without any logging flag."""
    from paddle_tpu.core import op_cache

    op_cache.reset_stats()
    for n in (3, 5, 7, 9):
        pt.to_tensor(np.ones((n, 4), np.float32)) + pt.to_tensor(
            np.ones((n, 4), np.float32))
    st = op_cache.stats()
    assert st["add"]["shape_keys"] == 4
    op_cache.reset_stats()
    assert op_cache.stats() == {}


# ---------------------------------------------------------------------------
# baseline suppression round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    def fn(x, w):
        return x.astype(jnp.float32) @ w

    rep = analysis.lint(fn, _s((64, 64), jnp.bfloat16),
                        _s((64, 64), jnp.float32))
    assert rep.findings
    base = Baseline()
    for f in rep.findings:
        base.add(f, "accepted for the round-trip test")
    path = str(tmp_path / "baseline.json")
    base.save(path)

    loaded = Baseline.load(path)
    assert loaded.suppressions == base.suppressions
    # same program -> fully suppressed
    rep2 = analysis.lint(fn, _s((64, 64), jnp.bfloat16),
                         _s((64, 64), jnp.float32))
    assert loaded.filter_new(rep2.findings) == []

    # a NEW finding (different shapes -> different fingerprint) gets through
    rep3 = analysis.lint(fn, _s((128, 128), jnp.bfloat16),
                         _s((128, 128), jnp.float32), program="fn")
    assert loaded.filter_new(rep3.findings)


def test_baseline_rejects_unknown_version(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text('{"version": 99, "suppressions": []}')
    with pytest.raises(ValueError):
        Baseline.load(str(path))


# ---------------------------------------------------------------------------
# jit.to_static compile hook
# ---------------------------------------------------------------------------

def test_to_static_hook_collects_reports():
    analysis.clear_reports()
    pt.set_flags({"FLAGS_graph_lint": True})
    try:
        w = pt.to_tensor(np.ones((16, 16), np.float32))
        w.stop_gradient = False

        @pt.jit.to_static
        def step(x):
            y = pt.matmul(x, w)
            return pt.mean(y)

        out = step(pt.to_tensor(np.ones((4, 16), np.float32)))
        assert np.isfinite(float(out))
        reps = step.lint_reports()
        assert len(reps) == 1 and reps[0].program == "step"
        assert any(r.program == "step" for r in analysis.reports())
    finally:
        pt.set_flags({"FLAGS_graph_lint": False})
        analysis.clear_reports()


def test_to_static_hook_off_by_default():
    analysis.clear_reports()

    @pt.jit.to_static
    def step(x):
        return x * 2

    step(pt.to_tensor(np.ones((4,), np.float32)))
    assert step.lint_reports() == []
    assert analysis.reports() == []


# ---------------------------------------------------------------------------
# CLI exit codes (in-process; targets=none keeps it fast — the full
# train/decode targets are exercised by the slow test below and the
# run_tests.sh gate)
# ---------------------------------------------------------------------------

def _cli():
    spec = importlib.util.spec_from_file_location(
        "graph_lint_cli", os.path.join(_REPO, "tools", "graph_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_exit_codes_fast(tmp_path, capsys):
    cli = _cli()
    # 0: nothing to lint, nothing new
    assert cli.run(["--targets", "none"]) == 0
    # 1: an injected bf16->fp32 promotion is a NEW finding
    assert cli.run(["--targets", "none", "--inject", "gl001"]) == 1
    out = capsys.readouterr().out
    assert "GL001" in out and "promoted_matmul" in out  # code + provenance
    # 1: dropping donation on a cache-shaped buffer
    assert cli.run(["--targets", "none", "--inject", "gl004"]) == 1
    out = capsys.readouterr().out
    assert "GL004" in out
    # 0: the injected finding is suppressed once baselined
    base = str(tmp_path / "b.json")
    assert cli.run(["--targets", "none", "--inject", "gl001",
                    "--write-baseline", base]) == 0
    assert cli.run(["--targets", "none", "--inject", "gl001",
                    "--baseline", base]) == 0
    # 2: internal error (unknown target), NOT a lint finding
    assert cli.run(["--targets", "bogus"]) == 2


@pytest.mark.slow
def test_cli_bench_models_clean_against_committed_baseline():
    """The acceptance gate: the bench GPT train step + decode engines lint
    clean against the committed baseline (exit 0)."""
    cli = _cli()
    assert cli.run(["--baseline"]) == 0


# ---------------------------------------------------------------------------
# the real fixes stay fixed: bf16 model programs keep bf16 matmuls
# ---------------------------------------------------------------------------

def test_bf16_decode_program_has_no_promoted_dots():
    """Regression for the satellite fix: a pure-bf16 stacked GPT's decode
    program must not silently run its projections in fp32."""
    from paddle_tpu.models import GPTStackedForPretraining, gpt_tiny

    analysis.clear_reports()
    pt.set_flags({"FLAGS_graph_lint": True})
    try:
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        pt.seed(0)
        m = GPTStackedForPretraining(cfg)
        pt.amp.decorate(m, level="O2", dtype="bfloat16")
        m.eval()
        ids = pt.to_tensor(np.arange(12, dtype=np.int64).reshape(2, 6) % cfg.vocab_size)
        m.generate(ids, max_new_tokens=2, max_seq_len=128,
                   cache_dtype="bfloat16")
        reps = [r for r in analysis.reports()
                if r.program in ("prefill_step", "decode_step")]
        assert reps
        bad = [f for r in reps for f in r.findings if f.code == "GL001"]
        assert bad == [], "\n".join(f.render() for f in bad)
    finally:
        pt.set_flags({"FLAGS_graph_lint": False})
        analysis.clear_reports()


# ---------------------------------------------------------------------------
# v3 comm passes: GL008-GL011 (one positive + one negative each)
# ---------------------------------------------------------------------------

def _axis_mesh(n, name="dp"):
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, host has {len(devs)}")
    return Mesh(np.asarray(devs[:n]), (name,))


def _shmap(body, mesh, in_specs, out_specs):
    from paddle_tpu.core import compat as _compat

    # check_vma off: the toy bodies reduce dp-varying values locally on
    # purpose (the lint passes care about the collectives, not the rep
    # typing), and the plain-psum binding keeps the test jax-version-stable
    return _compat.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)


def test_gl008_unoverlapped_collective_flagged():
    from jax.sharding import PartitionSpec as P

    mesh = _axis_mesh(2)

    def body(x, w):
        g = jax.lax.psum(x, "dp")
        r = g.sum()        # blocks on the wire immediately...
        h = x @ w          # ...with this independent dot still pending
        return r + h.sum()

    fn = _shmap(body, mesh, (P("dp", None), P()), P())
    cfg = LintConfig(gl008_min_pending_flops=1000)
    rep = analysis.lint(fn, _s((8, 64)), _s((64, 64)), config=cfg)
    gl8 = [f for f in rep.findings if f.code == "GL008"]
    assert gl8, rep.render()
    assert "psum" in gl8[0].detail


def test_gl008_overlapped_collective_clean():
    from jax.sharding import PartitionSpec as P

    mesh = _axis_mesh(2)

    def body(x, w):
        g = jax.lax.psum(x, "dp")
        h = x @ w          # independent dot between issue and consumer:
        return g.sum() + h.sum()  # the wire hides behind it (overlap)

    fn = _shmap(body, mesh, (P("dp", None), P()), P())
    cfg = LintConfig(gl008_min_pending_flops=1000)
    rep = analysis.lint(fn, _s((8, 64)), _s((64, 64)), config=cfg)
    assert "GL008" not in _codes(rep), rep.render()


def test_gl009_replicated_state_flagged_sharded_clean():
    from jax.sharding import PartitionSpec as P

    mesh = _axis_mesh(2)

    def body(x, m):
        return (x * 2).sum() + m.sum()

    cfg = LintConfig(gl009_min_bytes=1024)
    # m replicated over the manual dp axis -> fires, quoting the shard win
    rep = analysis.lint(_shmap(body, mesh, (P("dp", None), P()), P()),
                        _s((8, 64)), _s((64, 64)), config=cfg)
    gl9 = [f for f in rep.findings if f.code == "GL009"]
    assert gl9, rep.render()
    assert "dp" in gl9[0].detail and "invar[1]" in gl9[0].detail
    assert gl9[0].cost and "reclaimable" in gl9[0].cost
    # x sharded over dp never fires; sharding m silences the pass
    rep2 = analysis.lint(
        _shmap(body, mesh, (P("dp", None), P("dp", None)), P()),
        _s((8, 64)), _s((64, 64)), config=cfg)
    assert "GL009" not in _codes(rep2), rep2.render()


def test_gl010_misaligned_collective_payload_flagged():
    from jax.sharding import PartitionSpec as P

    mesh = _axis_mesh(2)

    def body(x):
        return jax.lax.psum(x, "dp")

    cfg = LintConfig(tile_min_bytes=64)
    # 3x129 f32: 387 elems don't split into 2 ring chunks AND the
    # trailing dim breaks (8, 128) tiling
    rep = analysis.lint(_shmap(body, mesh, (P(),), P()),
                        _s((3, 129)), config=cfg)
    gl10 = [f for f in rep.findings if f.code == "GL010"]
    assert gl10, rep.render()
    assert "psum" in gl10[0].detail
    # aligned payload (8x128, evenly chunked): clean
    rep2 = analysis.lint(_shmap(body, mesh, (P(),), P()),
                         _s((8, 128)), config=cfg)
    assert "GL010" not in [f.code for f in rep2.findings], rep2.render()


def test_gl011_degenerate_axis_flagged_real_axis_clean():
    from jax.sharding import PartitionSpec as P

    mesh1 = _axis_mesh(1, "one")

    def body(x):
        return jax.lax.psum(x, "one")

    rep = analysis.lint(_shmap(body, mesh1, (P(),), P()), _s((512,)))
    gl11 = [f for f in rep.findings if f.code == "GL011"]
    assert gl11, rep.render()
    assert gl11[0].severity == "info"

    mesh2 = _axis_mesh(2)

    def body2(x):
        return jax.lax.psum(x, "dp")

    rep2 = analysis.lint(_shmap(body2, mesh2, (P(),), P()), _s((512,)))
    assert "GL011" not in _codes(rep2), rep2.render()


def test_gl009_baseline_round_trip():
    """A GL009 finding suppresses through the fingerprint machinery like
    any v1 code: same program -> filtered; reshaped state -> NEW."""
    from jax.sharding import PartitionSpec as P

    mesh = _axis_mesh(2)

    def body(x, m):
        return (x * 2).sum() + m.sum()

    cfg = LintConfig(gl009_min_bytes=1024)
    fn = _shmap(body, mesh, (P("dp", None), P()), P())
    rep = analysis.lint(fn, _s((8, 64)), _s((64, 64)), config=cfg,
                        program="rt")
    gl9 = [f for f in rep.findings if f.code == "GL009"]
    assert gl9
    base = Baseline()
    for f in gl9:
        base.add(f, "round-trip")
    assert base.filter_new(gl9) == []
    rep2 = analysis.lint(fn, _s((8, 128)), _s((128, 128)), config=cfg,
                         program="rt")
    new = [f for f in base.filter_new(rep2.findings) if f.code == "GL009"]
    assert new, "reshaped replicated state must be a NEW finding"


def test_cli_inject_gl009_trips_and_baselines(tmp_path, capsys):
    cli = _cli()
    assert cli.run(["--targets", "none", "--inject", "gl009"]) == 1
    out = capsys.readouterr().out
    assert "GL009" in out and "inject:gl009" in out
    base = str(tmp_path / "b9.json")
    assert cli.run(["--targets", "none", "--inject", "gl009",
                    "--write-baseline", base]) == 0
    assert cli.run(["--targets", "none", "--inject", "gl009",
                    "--baseline", base]) == 0


def test_int8_fused_step_program_gl001_clean():
    """Regression pin for the int8 serving variant: the quantized hot
    path lints under its own program name (fused_step_int8 — explicit
    dequant + per-row requant must never read as a silent promotion)."""
    from paddle_tpu.models import GPTStackedForPretraining, gpt_tiny
    from paddle_tpu.serving import ServingEngine

    analysis.clear_reports()
    pt.set_flags({"FLAGS_graph_lint": True})
    try:
        pt.seed(0)
        cfg = gpt_tiny(hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTStackedForPretraining(cfg)
        pt.amp.decorate(m, level="O2", dtype="bfloat16")
        m.eval()
        eng = ServingEngine(m, num_slots=2, page_size=16, max_context=32,
                            kv_dtype="int8", weight_dtype="int8")
        try:
            eng.submit(np.arange(5, dtype=np.int64) % cfg.vocab_size, 3)
            eng.run_until_idle()
            reps = [r for r in eng.lint_reports()
                    if r.program == "fused_step_int8"]
            assert reps, "int8 engine did not lint under fused_step_int8"
            bad = [f for r in reps for f in r.findings
                   if f.code == "GL001"]
            assert bad == [], "\n".join(f.render() for f in bad)
        finally:
            eng.close()
    finally:
        pt.set_flags({"FLAGS_graph_lint": False})
        analysis.clear_reports()
