#!/bin/bash
# CPU test runner: sanitized env (no TPU site-hook), 8 virtual devices.
export JAX_PLATFORMS=cpu
export PYTHONPATH=$(python - << 'PY'
import os
print(os.pathsep.join([p for p in os.environ.get('PYTHONPATH','').split(os.pathsep) if p and 'axon' not in p]+['/root/repo']))
PY
)
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export JAX_COMPILATION_CACHE_DIR=/tmp/paddle_tpu_jax_cache
exec python -m pytest "$@"
