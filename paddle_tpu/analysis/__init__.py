"""Static analysis of traced programs (Graph Lint).

``analysis.lint(fn, *args)`` walks the jaxpr of any traceable function and
returns findings with stable codes (GL001-GL011), severities, and eqn
provenance; ``FLAGS_graph_lint`` / ``PADDLE_TPU_GRAPH_LINT=1`` lints every
``jit.to_static`` program at compile time; ``tools/graph_lint.py`` is the
CI gate over the bench models.  See docs/graph_lint.md.
"""
from . import autotune  # noqa: F401
from .codes import (  # noqa: F401
    CODES,
    SEVERITY_RANK,
    GateReason,
    decode_gate_reason,
    flash_gate_reason,
    misaligned_dims,
    padded_shape,
    padding_waste_elems,
)
from .cost_model import (  # noqa: F401
    COLLECTIVE_PRIMS,
    CollectiveCost,
    CostReport,
    EqnCost,
    HardwareSpec,
    chip_spec,
    clear_cost_reports,
    collective_axis_names,
    collective_hops,
    collective_wire_bytes,
    cost,
    cost_jaxpr,
    cost_reports,
    cost_static_program,
)
from .graph_lint import (  # noqa: F401
    Baseline,
    Finding,
    LintConfig,
    LintReport,
    churn_findings,
    clear_reports,
    lint,
    lint_jaxpr,
    lint_static_program,
    reports,
    set_announce,
)

__all__ = [
    "CODES", "SEVERITY_RANK", "GateReason", "decode_gate_reason",
    "flash_gate_reason", "misaligned_dims", "padded_shape",
    "padding_waste_elems",
    "COLLECTIVE_PRIMS", "CollectiveCost", "CostReport", "EqnCost",
    "HardwareSpec", "chip_spec", "clear_cost_reports",
    "collective_axis_names", "collective_hops", "collective_wire_bytes",
    "cost", "cost_jaxpr", "cost_reports",
    "cost_static_program", "autotune",
    "Baseline", "Finding", "LintConfig", "LintReport", "churn_findings",
    "clear_reports", "lint", "lint_jaxpr", "lint_static_program", "reports",
    "set_announce",
]
