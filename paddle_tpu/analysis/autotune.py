"""Measured-cost autotuner for the owned Pallas kernels.

The flash / decode / paged attention kernels used to hard-code their block
shapes (`codes.default_block`: largest 128-multiple divisor up to 512).  This
module graduates that guess into a search:

1. **Static candidate enumeration** — for a (kernel, shape, dtype) key,
   enumerate every *legal* configuration from the shared tile rules in
   ``analysis/codes.py`` (block sizes must be 128-multiple divisors of the
   sequence axis, query sublane rows must be 8-multiples) filtered by a
   static VMEM-footprint estimate.  Pure analysis — runs identically on
   CPU, never touches a device.
2. **Measured sweep** (TPU only) — time each candidate once on-device
   (``sweep``; the caller provides the timing closure) and persist the
   winner in a shape-keyed table.
3. **Dispatch** — kernels ask :func:`kernel_params` at call sites; a table
   hit returns the tuned config, a miss falls back to the historical
   hard-coded choice.  Explicit ``FLAGS_flash_block_*`` overrides still
   win over the table (user > tuner > default).

The table key discipline mirrors ``core/op_cache``: the key is the full
shape/dtype signature the kernel specializes on (``seq``/``max_seq``/
``page_size`` + ``head_dim`` + dtype name), so a lookup can never apply a
config tuned for a different specialization.  The table persists as JSON
(default: ``analysis/autotune_table.json`` next to this module, override
with ``PADDLE_TPU_AUTOTUNE_TABLE``) and **loads in validated replay
mode**: every entry is re-checked against the *current* static gates at
load time and entries that are no longer legal (rule changes, corrupted
files) are dropped with a warning — CI validates, it never times.
``tools/autotune.py --validate`` is the strict version (exit 1 on any
invalid entry), wired into run_tests.sh; the sweep itself runs via
``tools/autotune.py`` on a TPU host and ``tools/tpu_smoke.py``'s autotune
case.  See docs/graph_lint.md "v2: autotuner".
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .codes import (TILE_LANE, TILE_SUBLANE, decode_gate_reason,
                    default_block as _auto_block, flash_gate_reason,
                    paged_gate_reason, ragged_gate_reason)

__all__ = [
    "KERNELS", "REMAT_POLICIES", "enumerate_candidates", "default_params",
    "static_rank", "vmem_bytes_estimate", "table_key", "AutotuneTable",
    "table_path", "load_table", "reset", "kernel_params", "force",
    "set_entry", "validate_table", "sweep", "remat_params_to_config",
    "remat_config_to_params",
]

KERNELS = ("flash_attention", "decode_attention", "paged_attention",
           "ragged_paged_attention", "train_remat")

# train_remat: the measured remat-policy search over the stacked-GPT train
# step — not a Pallas kernel, but the same shape-keyed persisted-table
# discipline.  Candidates are (recompute_interval, recompute_policy) pairs
# encoded as ints (the table stores ints); policy index -> config string:
REMAT_POLICIES = (None, "full", "dots")
# interval 0 == remat off entirely (policy must be 0 then); k >= 1 groups
# k blocks per checkpoint boundary on the stacked scan (pp_spmd.scan_blocks)
_REMAT_MAX_INTERVAL = 8


def remat_params_to_config(params: Dict[str, int]):
    """Table entry -> (recompute_interval, recompute_policy) as
    GPTConfig understands them.  ``(0, None)`` means remat off."""
    interval = int(params.get("interval", 1))
    policy = REMAT_POLICIES[int(params.get("policy", 1))]
    if interval == 0:
        return 0, None
    return interval, policy


def remat_config_to_params(interval: int, policy) -> Dict[str, int]:
    if interval <= 0:
        return {"interval": 0, "policy": 0}
    if policy is None:
        policy = "full"
    return {"interval": int(interval),
            "policy": REMAT_POLICIES.index(policy)}

# static VMEM budget for candidate filtering: ~16 MiB/core physical, keep
# headroom for Mosaic's own buffers and semaphores
VMEM_BUDGET = 10 << 20

_Q_ROWS_CHOICES = (8, 16)  # query sublane-broadcast rows (8-multiples)
# ragged fused-step token blocks: how many flat query tokens pack into one
# work item's MXU pass (8-multiples; larger blocks amortize page DMAs over
# prefill runs, smaller ones waste fewer padded rows on decode tokens)
_TOKEN_BLOCK_CHOICES = (8, 16, 32)


def _itemsize(dtype: str) -> int:
    return {"bfloat16": 2, "float16": 2, "float32": 4, "bf16": 2,
            "f32": 4, "fp16": 2, "int8": 1}.get(str(dtype), 4)


def _dtype_key(dtype) -> str:
    """Canonical dtype token for table keys ('bfloat16', 'float32')."""
    import numpy as np

    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


def _legal_blocks(seq: int, cap: int = 1024) -> List[int]:
    """128-multiple divisors of ``seq`` up to ``cap`` — the block sizes
    the kernels' KV/Q blocking accepts (same rule the GL002 gates
    encode)."""
    seq = int(seq)
    return [b for b in range(TILE_LANE, min(seq, cap) + 1, TILE_LANE)
            if seq % b == 0]


# ---------------------------------------------------------------------------
# static VMEM footprint (per kernel, per candidate)
# ---------------------------------------------------------------------------

def vmem_bytes_estimate(kernel: str, shape: Dict[str, int], dtype: str,
                        params: Dict[str, int]) -> int:
    """Rough static VMEM footprint of one candidate: resident input/output
    blocks (double-buffered — Pallas pipelines the DMA) plus the fp32
    scratch accumulators.  Deliberately conservative; its job is to reject
    candidates that cannot fit, not to model occupancy."""
    if kernel == "train_remat":
        return 0  # whole-program HBM trade, not a VMEM-resident kernel
    it = _itemsize(dtype)
    d = int(shape["head_dim"])
    if kernel == "flash_attention":
        bq = int(params["block_q"])
        bkv = int(params["block_kv"])
        # fwd: q,o (bq·d), k,v (bkv·d), lse (8·bq); scratch acc bq·d + 2·bq·128
        fwd = 2 * ((2 * bq * d + 2 * bkv * d + 8 * bq) * it)
        fwd += (bq * d + 2 * bq * 128) * 4
        # bwd(dkv): q,do (bq·d), k,v (bkv·d), dk,dv out (bkv·d), lse+delta
        bwd = 2 * ((2 * bq * d + 4 * bkv * d + 16 * bq) * it)
        bwd += 2 * bkv * d * 4
        return max(fwd, bwd)
    if kernel == "decode_attention":
        qr = int(params.get("q_rows", 8))
        bkv = int(params["block_kv"])
        est = 2 * ((2 * qr * d + 2 * bkv * d) * it)
        est += (qr * d + 2 * qr * 128) * 4
        return est
    if kernel == "paged_attention":
        qr = int(params.get("q_rows", 8))
        ps = int(shape["page_size"])
        est = 2 * ((2 * qr * d + 2 * ps * d) * it)
        est += (qr * d + 2 * qr * 128) * 4
        return est
    if kernel == "ragged_paged_attention":
        tb = int(params.get("token_block", 8))
        ps = int(shape["page_size"])
        # q/out blocks (tb·d), one page of k/v (ps·d), fp32 scratch
        est = 2 * ((2 * tb * d + 2 * ps * d) * it)
        est += (tb * d + 2 * tb * 128) * 4
        return est
    raise ValueError(f"unknown kernel {kernel!r} (expected one of {KERNELS})")


# ---------------------------------------------------------------------------
# candidate enumeration (pure static analysis)
# ---------------------------------------------------------------------------

def enumerate_candidates(kernel: str, shape: Dict[str, int],
                         dtype: str) -> List[Dict[str, int]]:
    """Every legal configuration for (kernel, shape, dtype), from the
    shared tile rules + the VMEM estimate.  Empty when the kernel's own
    eligibility gate rejects the shape (then there is nothing to tune —
    the kernel would fall back to XLA anyway)."""
    d = int(shape.get("head_dim", 0))  # train_remat keys carry no head_dim
    out: List[Dict[str, int]] = []
    if kernel == "flash_attention":
        seq = int(shape["seq"])
        if flash_gate_reason(seq, d) is not None:
            return []
        for bq in _legal_blocks(seq):
            for bkv in _legal_blocks(seq):
                out.append({"block_q": bq, "block_kv": bkv})
    elif kernel == "decode_attention":
        seq = int(shape["max_seq"])
        if decode_gate_reason(seq, d) is not None:
            return []
        for bkv in _legal_blocks(seq):
            for qr in _Q_ROWS_CHOICES:
                out.append({"block_kv": bkv, "q_rows": qr})
    elif kernel == "paged_attention":
        ps = int(shape["page_size"])
        if paged_gate_reason(ps, d) is not None:
            return []
        for qr in _Q_ROWS_CHOICES:
            out.append({"q_rows": qr})
    elif kernel == "ragged_paged_attention":
        ps = int(shape["page_size"])
        if ragged_gate_reason(ps, d) is not None:
            return []
        for tb in _TOKEN_BLOCK_CHOICES:
            out.append({"token_block": tb})
    elif kernel == "train_remat":
        L = int(shape["layers"])
        out.append({"interval": 0, "policy": 0})  # remat off
        for k in range(1, min(L, _REMAT_MAX_INTERVAL) + 1):
            if L % k:
                continue  # grouped scan needs L % interval == 0
            for pol in (1, 2):  # full, dots
                out.append({"interval": k, "policy": pol})
    else:
        raise ValueError(
            f"unknown kernel {kernel!r} (expected one of {KERNELS})")
    return [p for p in out
            if vmem_bytes_estimate(kernel, shape, dtype, p) <= VMEM_BUDGET]


def default_params(kernel: str, shape: Dict[str, int],
                   dtype: str) -> Dict[str, int]:
    """Today's hard-coded configuration — what the kernels pick with no
    table entry.  Table misses fall back to exactly this."""
    if kernel == "flash_attention":
        b = _auto_block(int(shape["seq"]))
        return {"block_q": b, "block_kv": b}
    if kernel == "decode_attention":
        return {"block_kv": _auto_block(int(shape["max_seq"])), "q_rows": 8}
    if kernel == "paged_attention":
        return {"q_rows": 8}
    if kernel == "ragged_paged_attention":
        return {"token_block": 8}
    if kernel == "train_remat":
        # the historical bench default: full remat, per-block boundary
        return {"interval": 1, "policy": 1}
    raise ValueError(f"unknown kernel {kernel!r} (expected one of {KERNELS})")


def static_rank(kernel: str, shape: Dict[str, int], dtype: str,
                candidates: Optional[List[Dict[str, int]]] = None
                ) -> List[Dict[str, int]]:
    """Candidates ordered best-first by a static cost estimate: per-grid-
    step dispatch overhead (fewer, larger blocks win) with VMEM pressure
    as the tie-breaker.  This is the *prior* a measured sweep starts from
    — and the order ``tools/autotune.py --report`` prints; it never
    replaces a measurement."""
    cands = candidates if candidates is not None else enumerate_candidates(
        kernel, shape, dtype)

    def grid_steps(p: Dict[str, int]) -> int:
        if kernel == "flash_attention":
            seq = int(shape["seq"])
            return (seq // p["block_q"]) * (seq // p["block_kv"])
        if kernel == "decode_attention":
            return int(shape["max_seq"]) // p["block_kv"]
        if kernel == "train_remat":
            # prior: least recompute work first (off < dots < full), then
            # tighter boundaries (smaller interval = lower peak residency)
            return {0: 0, 2: 1, 1: 2}[p["policy"]] * 100 + p["interval"]
        return 1  # paged: the grid is fixed by max_pages

    return sorted(cands, key=lambda p: (
        grid_steps(p),
        vmem_bytes_estimate(kernel, shape, dtype, p),
        # deterministic final tie-break
        tuple(sorted(p.items())),
    ))


# ---------------------------------------------------------------------------
# the persisted table
# ---------------------------------------------------------------------------

def table_key(kernel: str, shape: Dict[str, int], dtype) -> str:
    """Shape-keyed lookup key, op_cache discipline: the full specialization
    signature, canonically ordered."""
    dims = ",".join(f"{k}={int(v)}" for k, v in sorted(shape.items()))
    return f"{kernel}|{dims}|{_dtype_key(dtype)}"


class AutotuneTable:
    """Shape-keyed winning configs.  Entries carry their provenance:
    ``source="measured"`` (an on-device sweep, with ``measured_us``) or
    ``source="static-default"`` (seeded from :func:`default_params` so
    dispatch-through-the-table is exercised before any chip timed
    anything)."""

    VERSION = 1

    def __init__(self):
        self.entries: Dict[str, Dict[str, Any]] = {}

    # -- mutation ----------------------------------------------------------
    def put(self, kernel: str, shape: Dict[str, int], dtype, params,
            measured_us: Optional[float] = None, source: str = "measured",
            device: str = ""):
        key = table_key(kernel, shape, dtype)
        self.entries[key] = {
            "kernel": kernel,
            "shape": {k: int(v) for k, v in sorted(shape.items())},
            "dtype": _dtype_key(dtype),
            "params": {k: int(v) for k, v in sorted(params.items())},
            "measured_us": (None if measured_us is None
                            else round(float(measured_us), 3)),
            "source": source,
            "device": device,
        }

    def get(self, kernel: str, shape: Dict[str, int],
            dtype) -> Optional[Dict[str, int]]:
        e = self.entries.get(table_key(kernel, shape, dtype))
        return dict(e["params"]) if e else None

    # -- persistence -------------------------------------------------------
    def save(self, path: str):
        data = {
            "version": self.VERSION,
            "entries": [self.entries[k] for k in sorted(self.entries)],
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "AutotuneTable":
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != cls.VERSION:
            raise ValueError(
                f"autotune table {path}: unsupported version "
                f"{data.get('version')}")
        t = cls()
        for e in data.get("entries", ()):
            t.put(e["kernel"], e["shape"], e["dtype"], e["params"],
                  measured_us=e.get("measured_us"),
                  source=e.get("source", "measured"),
                  device=e.get("device", ""))
        return t


def validate_table(table: AutotuneTable) -> List[str]:
    """Replay validation: every entry's params must be in the CURRENT
    static candidate set for its key.  Returns human-readable problems
    (empty = valid).  Pure static analysis — no device, no timing."""
    problems = []
    for key, e in sorted(table.entries.items()):
        try:
            cands = enumerate_candidates(e["kernel"], e["shape"], e["dtype"])
        except (ValueError, KeyError) as exc:
            problems.append(f"{key}: unenumerable entry ({exc})")
            continue
        if not cands:
            problems.append(
                f"{key}: shape fails the kernel's eligibility gate — an "
                "entry for it can never dispatch")
        elif e["params"] not in cands:
            problems.append(
                f"{key}: params {e['params']} are not in the legal "
                f"candidate set ({len(cands)} candidates)")
    return problems


# ---------------------------------------------------------------------------
# process-wide dispatch state
# ---------------------------------------------------------------------------

_DEFAULT_TABLE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "autotune_table.json")


def table_path() -> str:
    return os.environ.get("PADDLE_TPU_AUTOTUNE_TABLE", _DEFAULT_TABLE)


_lock = threading.RLock()
_loaded: Optional[AutotuneTable] = None
_load_failed = False
_forced: Dict[str, Dict[str, int]] = {}  # kernel -> params (sweep probe)


def load_table(path: Optional[str] = None,
               strict: bool = False) -> AutotuneTable:
    """Load + replay-validate the table at ``path`` (default:
    :func:`table_path`).  Invalid entries are dropped with one stderr
    warning (``strict=True`` raises instead — the CI gate).  A missing
    file is an empty table."""
    path = path or table_path()
    if not os.path.exists(path):
        return AutotuneTable()
    table = AutotuneTable.load(path)
    problems = validate_table(table)
    if problems:
        if strict:
            raise ValueError(
                f"autotune table {path}: {len(problems)} invalid entries:\n"
                + "\n".join("  " + p for p in problems))
        sys.stderr.write(
            f"[paddle_tpu.autotune] {path}: dropping {len(problems)} "
            "invalid entries (replay validation):\n"
            + "".join(f"  {p}\n" for p in problems))
        bad_keys = {p.split(":", 1)[0] for p in problems}
        for k in bad_keys:
            table.entries.pop(k, None)
    return table


def _table() -> AutotuneTable:
    global _loaded, _load_failed
    with _lock:
        if _loaded is None:
            try:
                _loaded = load_table()
            except Exception as e:  # noqa: BLE001 — a bad table must never
                # break kernel dispatch; the kernels fall back to defaults
                if not _load_failed:
                    sys.stderr.write(
                        f"[paddle_tpu.autotune] failed to load "
                        f"{table_path()}: {type(e).__name__}: {e}; kernels "
                        "use their hard-coded defaults\n")
                _load_failed = True
                _loaded = AutotuneTable()
        return _loaded


def reset():
    """Drop the loaded table (and any forced params) so the next lookup
    reloads from disk — tests point PADDLE_TPU_AUTOTUNE_TABLE at fixtures
    and call this."""
    global _loaded, _load_failed
    with _lock:
        _loaded = None
        _load_failed = False
        _forced.clear()


def set_entry(kernel: str, shape: Dict[str, int], dtype, params,
              **meta):
    """Insert an entry into the LIVE table (not persisted) — the sweep and
    tests use this; ``AutotuneTable.save`` persists."""
    with _lock:
        _table().put(kernel, shape, dtype, params, **meta)


@contextlib.contextmanager
def force(kernel: str, params: Dict[str, int]):
    """Force ``kernel`` to use ``params`` inside the context — how the
    sweep times one candidate through the kernels' public entry points.
    Wins over the table; loses to explicit FLAGS overrides (a user pin
    must beat the tuner)."""
    with _lock:
        prev = _forced.get(kernel)
        _forced[kernel] = dict(params)
    try:
        yield
    finally:
        with _lock:
            if prev is None:
                _forced.pop(kernel, None)
            else:
                _forced[kernel] = prev


def kernel_params(kernel: str, shape: Dict[str, int],
                  dtype) -> Optional[Dict[str, int]]:
    """The dispatch-time lookup the kernels call: forced params (sweep
    probe) > persisted table entry > ``None`` (kernel falls back to its
    hard-coded default).  Entries were replay-validated at load."""
    with _lock:
        f = _forced.get(kernel)
        if f is not None:
            return dict(f)
    return _table().get(kernel, shape, dtype)


# ---------------------------------------------------------------------------
# the measured sweep (orchestration only; callers own the timing closure)
# ---------------------------------------------------------------------------

def sweep(kernel: str, shape: Dict[str, int], dtype,
          timing_fn: Callable[[Dict[str, int]], float],
          table: Optional[AutotuneTable] = None,
          device: str = "") -> Tuple[Optional[Dict[str, int]],
                                     List[Tuple[Dict[str, int], float]]]:
    """Time every legal candidate once and record the winner.

    ``timing_fn(params) -> seconds`` runs the kernel with ``params``
    forced (use :func:`force`) and returns one measured execution; a
    candidate whose timing raises is skipped (some configs die in Mosaic
    for reasons no static model sees — that is *why* this is measured).
    Returns ``(winner_params_or_None, [(params, seconds|inf), ...])`` and
    records the winner in ``table`` (default: the live dispatch table).
    """
    results: List[Tuple[Dict[str, int], float]] = []
    for params in static_rank(kernel, shape, dtype):
        try:
            seconds = float(timing_fn(params))
        except Exception as e:  # noqa: BLE001 — a dead candidate is data
            sys.stderr.write(
                f"[paddle_tpu.autotune] {kernel} {params}: candidate "
                f"failed ({type(e).__name__}: {str(e)[:200]})\n")
            seconds = float("inf")
        results.append((params, seconds))
    timed = [(p, s) for p, s in results if s != float("inf")]
    if not timed:
        return None, results
    winner, best = min(timed, key=lambda ps: ps[1])
    tgt = table if table is not None else _table()
    with _lock:
        tgt.put(kernel, shape, dtype, winner, measured_us=best * 1e6,
                source="measured", device=device)
    return dict(winner), results
