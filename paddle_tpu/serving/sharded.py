"""Mesh-native serving: ``dp`` replica engines x ``mp`` tensor-parallel
chips behind ONE placement scheduler.

``ShardedServingEngine`` is the cluster front end of the PR-14 scheduler
split (docs/serving.md "Sharded serving"):

- it builds one ``('mp',)`` submesh per ``dp`` replica over disjoint
  device rows (``distributed/serving_mesh.replica_meshes``), gives each
  replica its OWN model copy (weights column/row-parallel over ``mp``,
  replicated across replicas) and its own :class:`ServingEngine` — pool,
  slots, admission, fault containment, and the donated fused step all
  per replica, compiled ONCE per replica as an SPMD program;
- the paged KV pool inside each replica is sharded per-head
  (``[num_pages, H/mp, page_size, D]`` per chip), the ragged/paged
  kernels run per head shard under ``shard_map``, and the only hot-path
  cross-chip reduce is the row-parallel post-attention/post-MLP
  projection all-reduce GSPMD inserts;
- ``submit`` goes through the placement layer
  (``serving/placement.py``): least-loaded replica wins, queue-depth
  backpressure is the signal, and a typed ``Overloaded`` shed happens
  only when EVERY replica backpressures.

Scaling shape: aggregate decode slots and page-pool HBM grow linearly
with ``dp`` (each replica owns a full pool on its own chips); per-chip
pool bytes shrink ~1/mp.  Greedy serving stays token-for-token equal to
the single-chip engine and to ``generate()`` — the parity suite in
tests/test_sharded_serving.py pins it for (dp, mp) in
{(1,2), (2,1), (2,2)} on the forced-8-device CPU mesh.
"""
from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

import numpy as np

from ..distributed import serving_mesh as _srv_mesh
from .engine import Request, RequestState, ServingEngine, ServingError
from .placement import LeastLoadedPlacement, PlacementScheduler

__all__ = ["ShardedServingEngine"]


class ShardedServingEngine:
    """``dp`` x ``mp`` sharded serving behind one submit/step interface.

    ``model`` becomes replica 0 (its parameters are committed to replica
    0's submesh — the engine takes placement ownership); further replicas
    are fresh instances loaded from its exact ``state_dict``
    (``model_factory`` overrides construction for classes whose
    ``__init__`` needs more than the config).  Engine knobs
    (``num_slots``, ``page_size``, pool sizing, fault containment, ...)
    pass through to every replica unchanged — they are per-replica
    quantities, so aggregate capacity is ``dp`` times each."""

    def __init__(self, model, *, dp: int = 1, mp: int = 1,
                 devices=None, model_factory: Optional[Callable] = None,
                 placement=None, engine_factory: Optional[Callable] = None,
                 **engine_kw):
        dp, mp = int(dp), int(mp)
        if mp > 1:
            # hard shard precondition, typed at construction (GL002
            # formatting) — not a shard_map crash deep in the first step
            _srv_mesh.validate_head_sharding(model.config.num_heads, mp)
        self.dp, self.mp = dp, mp
        self.meshes = _srv_mesh.replica_meshes(dp, mp, devices)
        self.replicas: List[ServingEngine] = []
        for i, mesh in enumerate(self.meshes):
            rm = model if i == 0 else _srv_mesh.clone_model(
                model, model_factory)
            _srv_mesh.shard_model_for_serving(rm, mesh)
            if engine_factory is not None:
                # replica-level composition hook: a speculative replica
                # (SpeculativeEngine + its own draft model clone) or a
                # LoRA-pooled replica (per-replica slab Tensors) —
                # docs/serving.md "Speculative decoding & multi-tenant
                # LoRA".  Signature: (model, mesh, index, **engine_kw).
                eng = engine_factory(rm, mesh, i, **engine_kw)
            else:
                eng = ServingEngine(rm, mesh=mesh, **engine_kw)
            self.replicas.append(eng)
        self.placement = PlacementScheduler(
            self.replicas, policy=placement or LeastLoadedPlacement())
        # per-tick replica stepping runs on one thread per replica (dp>1)
        # so the replicas' device work overlaps: each engine's step holds
        # only its own lock and drives only its own submesh, and the GIL
        # is released for the device execution + host fetch — strictly
        # sequential stepping would serialize the dp devices and break
        # the ~linear aggregate-tokens/s scaling on real hardware
        self._pool = (ThreadPoolExecutor(
            max_workers=dp, thread_name_prefix="sharded-serving-step")
            if dp > 1 else None)

    # -- submission (placement layer) --------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, **kwargs) -> Request:
        """Place the request on the least-loaded replica and queue it
        there.  Typed ``Overloaded`` only when ALL replicas shed; the
        seated replica's index rides on ``request.replica``."""
        return self.placement.submit(prompt, max_new_tokens, **kwargs)

    # -- the serving loop --------------------------------------------------
    def step(self) -> dict:
        """One cluster tick: every replica runs its own fused step (its
        own admission, pool and fault containment), concurrently across
        replicas when dp > 1.  Returns aggregate step metrics plus the
        per-replica list (replica order preserved)."""
        if self._pool is not None:
            per = list(self._pool.map(lambda e: e.step(), self.replicas))
        else:
            per = [eng.step() for eng in self.replicas]
        pages_used = sum(m["pages_used"] for m in per)
        pages_cap = sum(m["pages_capacity"] for m in per)
        agg = {
            "active_slots": sum(m["active_slots"] for m in per),
            "queue_depth": sum(m["queue_depth"] for m in per),
            "pages_used": pages_used,
            "pages_capacity": pages_cap,
            "occupancy": pages_used / pages_cap if pages_cap else 0.0,
            "replica_occupancy": [m["occupancy"] for m in per],
            "tokens_this_step": sum(m["tokens_this_step"] for m in per),
            "replicas": per,
        }
        return agg

    def run_until_idle(self, max_steps: Optional[int] = None) -> dict:
        """Step until every replica's queue and slots drain."""
        steps = 0
        while self.placement.pending():
            met = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if (not met["active_slots"] and not met["tokens_this_step"]
                    and self.placement.pending()):
                time.sleep(0.001)       # post-recovery backoff, any replica
        return self.metrics()

    def generate_batch(self, prompts, max_new_tokens: int = 32, *,
                       raise_on_failure: bool = True,
                       **kwargs) -> List[np.ndarray]:
        """Submit every prompt through placement, drain the cluster,
        return prompt+generated ids in submission order (the single-engine
        ``generate_batch`` contract, including the typed error on non-DONE
        terminals)."""
        reqs = [self.submit(p, max_new_tokens, **kwargs) for p in prompts]
        self.run_until_idle()
        bad = [r for r in reqs if r.state != RequestState.DONE]
        if bad and raise_on_failure:
            detail = ", ".join(f"request {r.id}: {r.state}" for r in bad)
            raise ServingError(
                f"generate_batch: {len(bad)}/{len(reqs)} request(s) did "
                f"not complete ({detail})") from bad[0].error
        return [r.output_ids() for r in reqs]

    # -- observability -----------------------------------------------------
    def metrics(self) -> dict:
        """Cluster metrics: summed counters/capacities (aggregate slots
        and page HBM scale linearly with ``dp`` — the acceptance
        criterion), per-chip pool bytes (shrink ~1/mp), and the full
        per-replica metrics list."""
        per = [eng.metrics() for eng in self.replicas]
        sum_keys = ("steps", "tokens", "admitted", "completed",
                    "fused_steps", "prefill_tokens", "failed", "cancelled",
                    "timed_out", "shed", "quarantined", "recoveries",
                    "rebuilds", "pages_used", "pages_capacity",
                    "active_slots", "queue_depth", "cache_bytes",
                    "work_items", "work_capacity", "block_rows",
                    "block_row_capacity", "padded_rows", "padded_flops",
                    # per-replica prefix caches (docs/serving.md "Prefix
                    # cache"): hits/misses sum exactly; hit RATE is
                    # re-derived from the sums below
                    "prefix_hits", "prefix_partial_hits", "prefix_misses",
                    "prefix_evictions", "prefix_cached_tokens",
                    "prefix_cache_pages", "prefix_cache_nodes",
                    "shared_pages")
        out = {k: sum(int(m.get(k, 0)) for m in per) for k in sum_keys}
        looked = (out["prefix_hits"] + out["prefix_partial_hits"]
                  + out["prefix_misses"])
        out["prefix_hit_rate"] = ((out["prefix_hits"]
                                   + out["prefix_partial_hits"]) / looked
                                  if looked else 0.0)
        # cluster-level sheds (all replicas backpressured) on top of the
        # replicas' own shed counters (queue-wait shedding etc.) — the
        # placement layer skips full replicas instead of probing their
        # submit, so one rejected request counts exactly once
        out["shed"] += self.placement.shed_total
        out["placement_shed"] = self.placement.shed_total
        out["dp"] = self.dp
        out["mp"] = self.mp
        out["slot_capacity"] = sum(e.num_slots for e in self.replicas)
        out["cache_bytes_per_chip"] = (per[0]["cache_bytes_per_chip"]
                                       if per else 0)
        out["routed"] = list(self.placement.routed)
        out["per_replica"] = per
        return out

    @property
    def compiled_programs(self) -> int:
        return sum(e.compiled_programs for e in self.replicas)

    def lint_reports(self):
        return [r for e in self.replicas for r in e.lint_reports()]

    def close(self):
        for eng in self.replicas:
            eng.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
