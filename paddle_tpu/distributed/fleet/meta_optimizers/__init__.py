"""Fleet meta-optimizers: LARS, DGC, LocalSGD.

Reference: python/paddle/distributed/fleet/meta_optimizers/
{lars_optimizer.py, dgc_optimizer.py, localsgd_optimizer.py} — there,
each wraps the inner optimizer by rewriting the static program (inserting
lars_momentum / dgc ops / program-level parameter syncs).

TPU-native redesign: each is an ordinary ``Optimizer`` whose update is a
pure jnp expression — under ``jit.to_static`` the whole thing fuses into
the train-step program, and the collectives (DGC's sparse all-reduce,
LocalSGD's parameter averaging) are the framework collective API, which
lowers to XLA collectives on a mesh and to the store-backed process-group
path across hosts.  ``fleet.distributed_optimizer`` applies them from
``DistributedStrategy.lars/dgc/localsgd`` exactly like the reference's
meta-optimizer selection pass.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ....ops import dispatch
from ....optimizer.optimizer import Optimizer
from ....tensor import Tensor

__all__ = ["LarsMomentum", "DGCMomentum", "LocalSGD", "GradientMerge",
           "apply_strategy_meta_optimizers"]


class LarsMomentum(Optimizer):
    """Layer-wise Adaptive Rate Scaling (reference lars_optimizer.py,
    phi lars_momentum kernel): local_lr = lr * coeff * ||w|| /
    (||g|| + lambda*||w|| + eps), momentum applied on the scaled grad."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=1e-9,
                 exclude_from_weight_decay=None, grad_clip=None, name=None):
        self._momentum = momentum
        self._coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = exclude_from_weight_decay or []
        super().__init__(learning_rate, parameters, None, grad_clip, name)

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _apply_one(self, p, g):
        lr = self._lr_value()
        v = self._get_accumulator("velocity", p)
        dispatch.note_read(v)
        pv = p._value.astype(jnp.float32)
        gv = g._value.astype(jnp.float32)
        wd = self._lars_wd
        name = p.name or ""
        if any(tag in name for tag in self._exclude):
            wd = 0.0
        w_norm = jnp.sqrt(jnp.sum(pv * pv))
        g_norm = jnp.sqrt(jnp.sum(gv * gv))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._coeff * w_norm / (g_norm + wd * w_norm + self._eps),
            jnp.asarray(lr, jnp.float32))
        new_v = self._momentum * v._value + local_lr * (gv + wd * pv)
        v._set_value(new_v)
        self._write_param(p, (pv - new_v).astype(p._value.dtype))


class DGCMomentum(Optimizer):
    """Deep Gradient Compression (reference dgc_optimizer.py + dgc_op):
    momentum correction + top-k% gradient sparsification; the residual
    (non-selected) gradient accumulates locally and is fed back on later
    steps.  On a mesh the DENSE all-reduce already happened inside SPMD
    autodiff, so the compression models the reference's semantics
    (momentum correction + delayed small gradients) in a compiler-friendly
    fixed-shape way: top-k by magnitude via a threshold from
    jnp.percentile — no dynamic shapes, XLA-compatible."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, sparsity=0.999, grad_clip=None,
                 name=None):
        self._momentum = momentum
        self._sparsity = float(sparsity)
        self._rampup_begin = int(rampup_begin_step)
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        # step count lives DEVICE-SIDE (like Adam's beta-power aux state):
        # a python int would be baked in at jit trace time and the
        # warmup->compression switch would never fire in a compiled step
        self._step_t = Tensor(jnp.zeros((), jnp.int32))

    def _create_accumulators(self, params):
        for p in params:
            self._add_accumulator("u", p)   # momentum-corrected velocity
            self._add_accumulator("v", p)   # local residual accumulator

    def step(self):
        dispatch.note_read(self._step_t)
        self._step_t._set_value(self._step_t._value + 1)
        super().step()

    def _apply_one(self, p, g):
        lr = self._lr_value()
        u = self._get_accumulator("u", p)
        v = self._get_accumulator("v", p)
        dispatch.note_read(u)
        dispatch.note_read(v)
        gv = g._value.astype(jnp.float32)
        # momentum correction (DGC eq.4): u = m*u + g ; v += u
        new_u = self._momentum * u._value + gv
        acc = v._value + new_u
        if p._value.size < 2:
            u._set_value(new_u)
            self._write_param(
                p, (p._value.astype(jnp.float32) - lr * new_u)
                .astype(p._value.dtype))
            return
        # top-k selection by magnitude threshold (k = 1 - sparsity)
        q = jnp.percentile(jnp.abs(acc).reshape(-1), self._sparsity * 100.0)
        mask = (jnp.abs(acc) >= q).astype(jnp.float32)
        # rampup: before rampup_begin_step the update is plain momentum
        # (mask == 1 everywhere, nothing withheld) — selected via a traced
        # predicate so compiled steps switch at the right step
        warm = self._step_t._value <= self._rampup_begin
        mask = jnp.where(warm, jnp.ones_like(mask), mask)
        sent = jnp.where(warm, new_u, acc * mask)
        # warmup keeps the full momentum buffer (mask is all-ones there, so
        # new_u * (1 - mask) would zero it and degenerate warmup to SGD);
        # only the compressed phase resets the selected entries
        u._set_value(jnp.where(warm, new_u, new_u * (1.0 - mask)))
        v._set_value(jnp.where(warm, v._value, acc * (1.0 - mask)))
        self._write_param(
            p, (p._value.astype(jnp.float32) - lr * sent)
            .astype(p._value.dtype))


class LocalSGD(Optimizer):
    """Local SGD (reference localsgd_optimizer.py): run k local steps,
    then average parameters across the data-parallel group.  On a mesh the
    SPMD program keeps params replicated (averaging is the identity), so
    the averaging collective engages on the cross-process group path —
    matching the reference's program-level broadcast/allreduce sync."""

    def __init__(self, inner_optimizer: Optimizer, k_steps=4, group=None,
                 name=None):
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._n = 0
        self._group = group
        # mirror the inner optimizer's parameter list; no own accumulators
        self._parameter_list = inner_optimizer._parameter_list
        self._accumulators = inner_optimizer._accumulators
        self._aux_state = inner_optimizer._aux_state
        self._grad_clip = None

    def step(self):
        self._inner.step()
        from ....jit.api import in_tracing

        if in_tracing():
            # under SPMD tracing params are REPLICATED on the mesh, so the
            # periodic average is the identity — nothing to insert in the
            # compiled program.  (Cross-process store-backed averaging is
            # host code and only exists on the eager path below.)
            return
        self._n += 1
        if self._n % self._k == 0:
            self._average_params()

    def _average_params(self):
        from ... import collective
        from ...env import get_world_size

        world = get_world_size(self._group)
        if world <= 1:
            return
        with dispatch.no_grad():
            for p in self._parameter_list:
                t = Tensor(p._value)
                collective.all_reduce(t, group=self._group)
                p._set_value((t._value / world).astype(p._value.dtype))

    def clear_grad(self):
        self._inner.clear_grad()

    def get_lr(self):
        return self._inner.get_lr()

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)


def apply_strategy_meta_optimizers(optimizer, strategy):
    """The reference's meta-optimizer selection pass
    (fleet/base/meta_optimizer_factory): DistributedStrategy flags pick a
    wrapped optimizer."""
    from ....optimizer.optimizers import SGD, Momentum

    if strategy is None:
        return optimizer
    if (getattr(strategy, "lars", False) or getattr(strategy, "dgc", False)) \
            and not isinstance(optimizer, (SGD, Momentum, LarsMomentum,
                                           DGCMomentum)):
        # the reference meta-optimizer pass applies LARS/DGC only to
        # momentum-family inner optimizers; silently replacing Adam's
        # update rule would change the training algorithm
        raise ValueError(
            f"strategy.lars/dgc requires a momentum-family optimizer, got "
            f"{type(optimizer).__name__}")
    if getattr(strategy, "lars", False):
        cfg = getattr(strategy, "lars_configs", {}) or {}
        return LarsMomentum(
            learning_rate=optimizer._learning_rate,
            momentum=getattr(optimizer, "_momentum", 0.9),
            parameters=optimizer._parameter_list,
            lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
            epsilon=cfg.get("epsilon", 1e-9),
            exclude_from_weight_decay=cfg.get("exclude_from_weight_decay"),
            grad_clip=optimizer._grad_clip)
    if getattr(strategy, "dgc", False):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        return DGCMomentum(
            learning_rate=optimizer._learning_rate,
            momentum=getattr(optimizer, "_momentum", 0.9),
            parameters=optimizer._parameter_list,
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            sparsity=(cfg.get("sparsity", [0.999]) or [0.999])[-1],
            grad_clip=optimizer._grad_clip)
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        return LocalSGD(optimizer, k_steps=cfg.get("k_steps", 4))
    if getattr(strategy, "gradient_merge", False):
        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        k = int(cfg.get("k_steps", 1))
        if k > 1:
            return GradientMerge(optimizer, k_steps=k,
                                 avg=cfg.get("avg", True))
    return optimizer


class GradientMerge:
    """Gradient merging / accumulation (reference
    fleet/meta_optimizers/gradient_merge_optimizer.py + the
    GradientMergePass): accumulate k micro-steps of gradients, apply the
    inner optimizer once per k.

    TPU-native: the k-step gate is a traced predicate on a device-side
    counter; on non-apply steps EVERY state tensor the inner step mutated
    (params, moments, aux powers, master weights) is rolled back via
    jnp.where, so the whole wrapper functionalizes into one compiled
    train step with no python-side control flow."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = avg
        self._parameter_list = inner_optimizer._parameter_list
        self._accumulators = inner_optimizer._accumulators
        self._aux_state = inner_optimizer._aux_state
        self._grad_clip = None
        self._step_t = Tensor(jnp.zeros((), jnp.int32))
        self._acc = {id(p): self._make_acc(p) for p in self._parameter_list}

    @staticmethod
    def _make_acc(p):
        import jax

        raw = jnp.zeros(p._value.shape, jnp.float32)
        # inherit the param's MESH layout (like _add_accumulator): a
        # ZeRO/TP-sharded param keeps its gradient accumulator sharded
        sh = getattr(p._value, "sharding", None)
        if isinstance(sh, jax.sharding.NamedSharding):
            raw = jax.device_put(raw, sh)
        return Tensor(raw)

    def _state_tensors(self):
        out = list(self._parameter_list)
        for store in self._inner._accumulators.values():
            out.extend(store.values())
        out.extend(t for t in self._inner._aux_state.values()
                   if isinstance(t, Tensor))
        out.extend(getattr(self._inner, "_master", {}).values())
        # any other device-side state the inner optimizer keeps as a plain
        # attribute (e.g. DGC's _step_t) must roll back too
        seen = {id(t) for t in out}
        for v in vars(self._inner).values():
            if isinstance(v, Tensor) and id(v) not in seen:
                out.append(v)
                seen.add(id(v))
        return out

    @dispatch.no_grad()
    def step(self):
        k = self._k
        if k <= 1:
            self._inner.step()
            return
        dispatch.note_read(self._step_t)
        new_step = self._step_t._value + 1
        self._step_t._set_value(new_step)
        apply = (new_step % k) == 0
        # accumulate this micro-step's grads; feed the merged grad in
        from ....tensor import Tensor as _T

        for p in self._parameter_list:
            if p.grad is None:
                continue
            acc = self._acc[id(p)]
            dispatch.note_read(acc)
            acc._set_value(acc._value + p.grad._value.astype(jnp.float32))
            merged = acc._value / k if self._avg else acc._value
            p.grad = _T(merged.astype(p.grad._value.dtype))
        snapshot = [(t, t._value) for t in self._state_tensors()]
        self._inner.step()
        # non-apply steps: roll back every mutated state tensor
        for t, old in snapshot:
            t._set_value(jnp.where(apply, t._value, old))
        for acc in self._acc.values():
            acc._set_value(jnp.where(apply, jnp.zeros_like(acc._value),
                                     acc._value))

    def clear_grad(self):
        self._inner.clear_grad()

    def get_lr(self):
        return self._inner.get_lr()

    def state_dict(self):
        # in-window accumulation state checkpoints too: resuming
        # mid-window must not discard partial gradient sums or misalign
        # the k gate
        sd = dict(self._inner.state_dict())
        sd["gradient_merge"] = {
            "step": self._step_t.numpy(),
            "acc": [self._acc[id(p)].numpy()
                    for p in self._parameter_list],
        }
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        gm = sd.pop("gradient_merge", None)
        self._inner.set_state_dict(sd)
        if gm is not None:
            self._step_t._set_value(jnp.asarray(gm["step"]))
            for p, a in zip(self._parameter_list, gm["acc"]):
                self._acc[id(p)]._set_value(jnp.asarray(a))
