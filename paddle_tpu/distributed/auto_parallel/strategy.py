"""auto_parallel Strategy (reference: auto_parallel/strategy.py +
constants.py — nested config objects with an `enable` switch per pass)."""
from __future__ import annotations


class _Config:
    _fields = {}

    def __init__(self, **kw):
        unknown = set(kw) - set(self._fields)
        if unknown:
            raise ValueError(
                f"unknown {type(self).__name__} keys: {sorted(unknown)}")
        for k, v in {**self._fields, **kw}.items():
            setattr(self, k, v)

    def to_dict(self):
        return {k: getattr(self, k) for k in self._fields}


class AMPConfig(_Config):
    _fields = {"enable": False, "dtype": "bfloat16", "level": "O1",
               "init_loss_scaling": 32768.0, "custom_white_list": None,
               "custom_black_list": None, "use_master_weights": True}


class RecomputeConfig(_Config):
    _fields = {"enable": False, "checkpoints": None, "refined_ops_patterns": None}


class ShardingConfig(_Config):
    _fields = {"enable": False, "stage": 1, "degree": 1,
               "overlap_grad_comm": True}


class GradientMergeConfig(_Config):
    _fields = {"enable": False, "k_steps": 1, "avg": True}


class PipelineConfig(_Config):
    _fields = {"enable": False, "schedule_mode": "1F1B",
               "micro_batch_size": 1, "accumulate_steps": 1}


class MPConfig(_Config):
    _fields = {"enable": False, "degree": 1}


class Strategy(_Config):
    """reference auto_parallel/strategy.py Strategy."""

    _fields = {"auto_mode": "semi", "seed": None, "split_data": True}

    _nested = {"amp": AMPConfig, "recompute": RecomputeConfig,
               "sharding": ShardingConfig, "gradient_merge": GradientMergeConfig,
               "pipeline": PipelineConfig, "mp": MPConfig}

    def __init__(self, config=None):
        config = dict(config or {})
        nested_cfg = {k: config.pop(k) for k in list(config) if k in self._nested}
        unknown = set(config) - set(self._fields)
        if unknown:
            raise ValueError(f"unknown Strategy keys: {sorted(unknown)}")
        super().__init__(**config)
        for name, cls in self._nested.items():
            sub = nested_cfg.get(name, {})
            if isinstance(sub, _Config):
                setattr(self, name, sub)
            else:
                setattr(self, name, cls(**sub))
