"""io: Dataset / DataLoader / samplers.

Reference: python/paddle/io/ (reader.py:218 DataLoader, multiprocess worker
loop dataloader_iter.py:451). TPU-native: host-side numpy batching with a
background prefetch thread feeding the async XLA dispatch queue; multiprocess
workers use the same worker-loop design when num_workers>0.
"""
from .dataset import ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset, Subset, TensorDataset, random_split  # noqa: F401
from .sampler import BatchSampler, DistributedBatchSampler, RandomSampler, Sampler, SequenceSampler, WeightedRandomSampler  # noqa: F401
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .device_prefetch import DevicePrefetcher  # noqa: F401
