"""Error enforcement.

TPU-native equivalent of PADDLE_ENFORCE / phi error codes
(reference: paddle/phi/core/enforce.h, paddle/phi/core/errors.h). Python-level
framework errors carry a categorized type and a readable message; we keep the
category taxonomy so user-facing behavior matches the reference.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PreconditionNotMetError",
    "UnimplementedError",
    "UnavailableError",
    "enforce",
    "enforce_eq",
    "enforce_shape_match",
]


class EnforceNotMet(RuntimeError):
    """Base framework error (reference enforce.h:EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


def enforce(cond, msg: str = "", err=InvalidArgumentError):
    if not cond:
        raise err(msg or "enforce failed")


def enforce_eq(a, b, msg: str = ""):
    if a != b:
        raise InvalidArgumentError(f"{msg or 'values must be equal'}: got {a!r} vs {b!r}")


def enforce_shape_match(shape_a, shape_b, msg: str = ""):
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(
            f"{msg or 'shape mismatch'}: {tuple(shape_a)} vs {tuple(shape_b)}"
        )
