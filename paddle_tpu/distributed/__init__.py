"""distributed namespace (reference: python/paddle/distributed/__init__.py)."""
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized  # noqa: F401
from .mesh import HYBRID_AXES, build_mesh, get_mesh, has_mesh, named_sharding, set_mesh  # noqa: F401
from .group import Group, get_group, new_group  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_gather_into_tensor,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    get_backend,
    irecv,
    isend,
    p2p_push,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from .parallel import DataParallel  # noqa: F401
from . import serving_mesh  # noqa: F401  (mesh-native serving helpers)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel.api import shard_tensor, shard_op, dtensor_from_fn  # noqa: F401
from .auto_parallel.process_mesh import ProcessMesh  # noqa: F401
from . import stream  # noqa: F401
from .collective import (  # noqa: F401
    P2POp, all_gather_object, batch_isend_irecv, broadcast_object_list,
    destroy_process_group, gather, scatter_object_list, wait,
)
from .auto_parallel.api import reshard  # noqa: F401
from . import fault_tolerance  # noqa: F401
from .errors import (  # noqa: F401
    CollectiveTimeoutError,
    DistributedError,
    PeerLostError,
    RendezvousInvalidated,
    StoreUnavailableError,
)
from .fleet.elastic import ElasticRunResult, run_elastic  # noqa: F401
