"""device namespace (reference: python/paddle/device/)."""
from ..core.memory import (  # noqa: F401
    max_memory_allocated,
    memory_allocated,
    memory_stats,
    memory_summary,
)
from ..core.place import (  # noqa: F401
    CPUPlace,
    Place,
    TPUPlace,
    current_place,
    device_count,
    get_device,
    is_compiled_with_tpu,
    set_device,
)


from . import plugin  # noqa: F401


def get_all_device_type():
    types = ["cpu"]
    if is_compiled_with_tpu():
        types.append("tpu")
    return types


def get_all_custom_device_type():
    """Device types added through the plugin boundary (reference
    device_manager GetAllCustomDeviceTypes)."""
    builtin = set(get_all_device_type())
    return [t for t in plugin.registered_types() if t not in builtin]


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in get_all_custom_device_type()


def get_available_device():
    return [f"{t}:{i}" for t in get_all_device_type() for i in range(device_count(t) or 1)]


def synchronize(device=None):
    """Block until all queued device work completes (analog of
    cudaDeviceSynchronize; jax exposes this as barrier on async dispatch)."""
    import jax

    try:
        jax.block_until_ready(jax.numpy.zeros(()))
    except Exception:
        pass
