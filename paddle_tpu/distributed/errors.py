"""Typed distributed-failure taxonomy (docs/distributed_faults.md).

Reference: paddle/fluid/distributed turns peer and store failures into
gRPC status codes; here every way a multi-host job can lose a peer or
its rendezvous store surfaces as ONE of these types, so callers
(run_elastic, the serving control plane, user training loops) can write
`except PeerLostError` instead of parsing RuntimeError strings.

Layering: :class:`StoreUnavailableError` is *defined* in
``core/native/tcp_store.py`` (the layer that owns store transport) and
re-exported here so the whole taxonomy is importable from one place.

- :class:`PeerLostError` — the failure detector (ElasticManager)
  declared one or more peer ranks dead while we were waiting on them.
  Carries ``.ranks``; raised within ~2x the detector TTL instead of
  blocking for the full collective timeout.
- :class:`CollectiveTimeoutError` — a collective/barrier/p2p wait ran
  out its deadline with every pending peer still *alive* (subclass of
  ``TimeoutError`` for back-compat with callers catching that).
- :class:`RendezvousInvalidated` — another rank requested a new
  generation (restart/join) while we were mid-collective; the current
  generation's keys are stale and the caller must re-rendezvous.
- :class:`StoreUnavailableError` — a store op kept failing after the
  bounded jittered-backoff retry budget (transport down, master dead).
"""
from __future__ import annotations

from typing import Sequence

from ..core.native.tcp_store import StoreUnavailableError  # noqa: F401

__all__ = [
    "DistributedError",
    "PeerLostError",
    "CollectiveTimeoutError",
    "RendezvousInvalidated",
    "StoreUnavailableError",
]


class DistributedError(RuntimeError):
    """Base of the distributed fault taxonomy."""


class PeerLostError(DistributedError):
    """Peer rank(s) stopped heartbeating while we were waiting on them.

    ``ranks`` is the sorted list of lost ranks; ``what`` names the
    operation that was pending on them."""

    def __init__(self, ranks: Sequence[int], what: str = "collective"):
        self.ranks = sorted(int(r) for r in ranks)
        self.what = what
        super().__init__(
            f"peer rank(s) {self.ranks} lost during {what} "
            "(missed heartbeats past the failure-detector TTL)")


class CollectiveTimeoutError(DistributedError, TimeoutError):
    """A collective wait expired with all pending peers still alive."""


class RendezvousInvalidated(DistributedError):
    """A new rendezvous was requested; the current generation is stale.

    Raised from inside collective waits when the store's rendezvous
    request counter moves past the one recorded at this process's last
    rendezvous — e.g. a restarted rank announcing itself.  Recovery:
    re-rendezvous at the new generation (run_elastic does this)."""
