"""Admission layer: the per-replica continuous-batching slot scheduler
(host-side bookkeeping).

One half of the PR-14 scheduler split (docs/serving.md "Sharded
serving"): ADMISSION — pages, slots, queues, backpressure — is a
per-replica concern and lives here; PLACEMENT — which ``dp`` replica
seats a request at all — is a cluster-level concern and lives in
``serving/placement.py``.  A single-replica engine uses this layer alone
(``serving/scheduler.py`` re-exports both for compatibility).

A fixed number of *slots* share one compiled fused step; the scheduler
owns which request occupies which slot, each slot's page-table row,
position, and not-yet-prefilled prompt remainder, and the block-pool
accounting:

- **admission** reserves every page a request can ever touch up front
  (``ceil((prompt + max_new_tokens) / page_size)``).  All-or-nothing: a
  request the pool cannot fully serve stays queued (backpressure) — a
  mid-decode out-of-pages condition therefore cannot exist, so live slots
  are never corrupted or preempted by page exhaustion.
- **per-step token planning** (``plan_step``) is first-class *variable
  tokens per step*: each tick, a seated slot contributes either one
  decode token or a budgeted run of prefill tokens from its pending
  prompt — the counts vary freely because the page math is keyed on
  TOKENS, not phases (admission already reserved every page any split
  can touch).  ROADMAP item 5 (speculative decoding, per-request LoRA)
  builds on the same path: ``advance(idx, n)`` accepts any n.
- **retirement** frees the slot's pages back to the allocator immediately
  (they are reusable the same step) and zeroes its table row to the null
  page.

The numpy arrays (``tables`` [num_slots, max_pages] int32, ``positions``
[num_slots] int32) are the exact host mirrors the engine ships to the
jitted step each call — fixed shapes, so the step never retraces as the
request mix churns.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .paged_cache import NULL_PAGE, BlockAllocator, pages_for_tokens

__all__ = ["Slot", "AdmissionScheduler", "Scheduler", "StepWork"]


class Slot:
    """One decode slot: the request occupying it + its page reservation.

    ``pending`` holds the prompt tokens not yet written into the pool
    (set at admission, consumed by the fused step's prefill runs); an
    empty/None pending means the slot is decoding.  ``seq`` is the
    admission sequence number — ``plan_step`` drains the prefill budget
    oldest-admission-first, so slot INDEX (which admission reuses as soon
    as a slot frees) never decides who prefills.

    ``shared`` counts the slot's LEADING pages that live in the prefix
    cache (spliced in at admission on a hit, or registered at harvest
    once completed — pages complete strictly in order, so shared pages
    are always a prefix of ``pages``); ``nodes`` holds the cache nodes
    the slot keeps reader references on, released at retirement.  A slot
    never writes its first ``shared`` pages — that is the COW ownership
    rule (serving/prefix_cache.py)."""

    __slots__ = ("request", "pages", "pos", "pending", "seq",
                 "shared", "nodes")

    def __init__(self, request, pages: List[int], pos: int = 0,
                 pending: Optional[np.ndarray] = None, seq: int = 0,
                 shared: int = 0, nodes: Optional[list] = None):
        self.request = request
        self.pages = pages
        self.pos = pos       # tokens written into the slot's pages so far
        self.pending = pending
        self.seq = seq
        self.shared = shared
        self.nodes = nodes if nodes is not None else []


class StepWork:
    """One slot's share of a fused step: ``count`` tokens starting at
    absolute position ``base`` — a prefill run (``kind='prefill'``,
    ``completes`` when it exhausts the slot's pending prompt, so the
    step's sampled token is the request's FIRST generated token), one
    decode token (``kind='decode'``), or a speculative verification run
    (``kind='verify'``: the slot's last sampled token plus the draft
    model's k proposals — ``count = 1 + k`` — whose accepted prefix the
    engine commits via ``advance(idx, n_accepted + 1)``; see
    serving/speculative.py).  ``drafts`` carries the proposed token ids
    on verify runs (None otherwise)."""

    __slots__ = ("slot", "kind", "count", "base", "completes", "drafts")

    def __init__(self, slot: int, kind: str, count: int, base: int,
                 completes: bool, drafts=None):
        self.slot = slot
        self.kind = kind
        self.count = count
        self.base = base
        self.completes = completes
        self.drafts = drafts

    @property
    def has_output(self) -> bool:
        """Whether this run samples a token (decode/verify always; a
        prefill run only when it completes the prompt — mid-prefill runs
        emit nothing)."""
        return self.kind in ("decode", "verify") or self.completes

    def __repr__(self) -> str:
        return (f"StepWork(slot={self.slot}, {self.kind}, count={self.count},"
                f" base={self.base}, completes={self.completes})")


class AdmissionScheduler:
    def __init__(self, num_slots: int, max_pages_per_slot: int,
                 page_size: int, allocator: BlockAllocator):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.max_pages_per_slot = max_pages_per_slot
        self.page_size = page_size
        self.allocator = allocator
        self.slots: List[Optional[Slot]] = [None] * num_slots
        self.tables = np.full((num_slots, max_pages_per_slot), NULL_PAGE,
                              np.int32)
        self.positions = np.zeros((num_slots,), np.int32)
        self._admit_seq = 0          # monotonic admission counter (fairness)
        # optional global prefix cache (serving/prefix_cache.py) — the
        # engine installs it; retirement releases slot references here
        self.prefix_cache = None

    # -- queries -----------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def free_slot_indices(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def seated(self) -> List[Tuple[int, Slot]]:
        """(index, slot) of every occupied slot — snapshot list, safe to
        retire slots while iterating (the reap/recovery paths do)."""
        return [(i, s) for i, s in enumerate(self.slots) if s is not None]

    def active_mask(self) -> np.ndarray:
        return np.array([s is not None for s in self.slots], bool)

    @property
    def occupancy(self) -> float:
        """Fraction of the allocatable pool currently reserved."""
        cap = self.allocator.capacity
        return self.allocator.used_pages / cap if cap else 0.0

    def pages_needed(self, total_tokens: int) -> int:
        return pages_for_tokens(total_tokens, self.page_size)

    # -- admission / retirement --------------------------------------------
    def try_admit(self, request, total_tokens: int, cached_pages=(),
                  cached_nodes=(), n_cached: int = 0) -> Optional[int]:
        """Seat ``request`` in a free slot with pages reserved for
        ``total_tokens``; None (nothing changed) when no slot is free, the
        request cannot fit a slot's table, or the pool lacks pages.

        A prefix-cache hit passes the matched ``cached_pages`` (reader
        references already taken on ``cached_nodes``) and ``n_cached``
        tokens they hold: the TAIL-ONLY reservation allocates just
        ``pages_needed(total) - len(cached_pages)`` fresh pages, the
        cached pages are spliced into the front of the table row, and the
        slot seats at position ``n_cached`` so prefill starts at the
        first uncached token.  On None the caller still owns the
        references (release them before requeueing)."""
        free = self.free_slot_indices()
        if not free:
            return None
        n = self.pages_needed(total_tokens)
        if n > self.max_pages_per_slot:
            raise ValueError(
                f"request needs {n} pages but a slot holds at most "
                f"{self.max_pages_per_slot} (max_context "
                f"{self.max_pages_per_slot * self.page_size})")
        n_shared = len(cached_pages)
        tail = self.allocator.alloc(n - n_shared)
        if tail is None:
            return None          # pool backpressure: stays queued
        pages = list(cached_pages) + tail
        idx = free[0]
        self.slots[idx] = Slot(request, pages, pos=int(n_cached),
                               seq=self._admit_seq, shared=n_shared,
                               nodes=list(cached_nodes))
        self._admit_seq += 1
        row = np.full((self.max_pages_per_slot,), NULL_PAGE, np.int32)
        row[:n] = pages
        self.tables[idx] = row
        self.positions[idx] = int(n_cached)
        return idx

    def adopt(self, request, pages: List[int], pos: int) -> Optional[int]:
        """Seat a request whose pages were transferred in from another
        replica (serving/disagg.py hand-off).  The pages must ALREADY sit
        in this pool's allocated ledger — the transfer commits its
        destination-side reservation (``commit_spec``) before seating, so
        adoption touches no allocator state; it only writes the slot and
        the table/position mirrors.  Seats at ``pos`` (every KV position
        the source wrote) with no pending prompt: the slot decodes from
        its first step here.  None when no slot is free (caller rolls the
        transfer back)."""
        free = self.free_slot_indices()
        if not free:
            return None
        if len(pages) > self.max_pages_per_slot:
            raise ValueError(
                f"transferred request holds {len(pages)} pages but a slot "
                f"holds at most {self.max_pages_per_slot}")
        idx = free[0]
        self.slots[idx] = Slot(request, list(pages), pos=int(pos),
                               seq=self._admit_seq, shared=0, nodes=[])
        self._admit_seq += 1
        row = np.full((self.max_pages_per_slot,), NULL_PAGE, np.int32)
        row[:len(pages)] = pages
        self.tables[idx] = row
        self.positions[idx] = int(pos)
        return idx

    def retire(self, idx: int):
        """Release slot ``idx``: private pages back to the pool NOW,
        reader references on shared (prefix-cache) pages dropped, table
        row to the null page, position to 0 (the inactive-slot
        encoding)."""
        slot = self.slots[idx]
        if slot is None:
            raise ValueError(f"retire({idx}): slot is already free")
        if slot.nodes:
            self.prefix_cache.release(slot.nodes)
        self.allocator.free(slot.pages[slot.shared:])
        self.slots[idx] = None
        self.tables[idx] = NULL_PAGE
        self.positions[idx] = 0

    def reset_mirrors(self):
        """Re-derive the host mirrors from the slot list (engine recovery:
        after every implicated slot is retired, the mirrors must encode
        exactly the inactive-slot pattern the fresh pool expects)."""
        assert all(s is None for s in self.slots), \
            "reset_mirrors with seated requests would corrupt their tables"
        self.tables[:] = NULL_PAGE
        self.positions[:] = 0

    def advance(self, idx: int, n: int = 1):
        """Record ``n`` more tokens written into slot ``idx`` (any n — the
        variable-tokens-per-step contract; the pages those tokens touch
        were reserved at admission)."""
        slot = self.slots[idx]
        assert slot is not None
        slot.pos += n
        self.positions[idx] = slot.pos

    # -- variable tokens per step (the fused mixed prefill/decode plan) ----
    def plan_step(self, prefill_token_budget: int) -> List[StepWork]:
        """Plan one fused step: every seated slot contributes a
        :class:`StepWork` — a run of up to the remaining shared
        ``prefill_token_budget`` pending-prompt tokens, or one decode
        token.  Slots are visited OLDEST ADMISSION FIRST (``Slot.seq``,
        not slot index — admission reuses a freed index immediately, so
        index order would let a low-index slot that churns through
        budget-sized prompts starve an older mid-prefill slot forever);
        a pending slot that gets no budget this tick simply waits (its
        entry is omitted).  The plan never touches allocator or mirror
        state — it is pure bookkeeping the engine turns into the step's
        flat token arrays, and it only commits (``advance`` + pending
        consumption) after the step succeeds, which is what makes a
        failed step's retry idempotent."""
        budget = int(prefill_token_budget)
        work: List[StepWork] = []
        for i, slot in sorted(self.seated(), key=lambda t: t[1].seq):
            if slot.pending is not None and len(slot.pending):
                if budget <= 0:
                    continue
                k = min(budget, len(slot.pending))
                work.append(StepWork(i, "prefill", k, slot.pos,
                                     k == len(slot.pending)))
                budget -= k
            else:
                work.append(StepWork(i, "decode", 1, slot.pos, False))
        return work


# Historical name: before the placement/admission split (PR 14) this class
# WAS serving/scheduler.py's ``Scheduler``.  Kept as an alias — engine
# internals, tests, and external callers hold ``engine.scheduler``.
Scheduler = AdmissionScheduler
