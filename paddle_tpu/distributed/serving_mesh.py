"""Serving-mesh helpers: the named-axes machinery for mesh-native serving.

The serving engine's fused step becomes an SPMD program over a small
two-axis geometry (docs/serving.md "Sharded serving"):

- ``mp`` — tensor parallelism INSIDE one replica: the paged KV pool is
  sharded per-head (``[num_pages, H/mp, page_size, D]`` per chip), the
  ragged/paged attention kernels run per head shard under ``shard_map``,
  and the model weights are partitioned Megatron column/row-parallel via
  NamedSharding (GSPMD inserts the one row-parallel all-reduce after the
  post-attention / post-MLP projections — the only cross-chip reduce on
  the hot path).
- ``dp`` — replica scaling: each dp replica owns its OWN pool, slots and
  compiled fused step on a disjoint ``mp`` submesh; the placement layer
  (``serving/placement.py``) routes requests across replicas, so
  aggregate slots and page HBM grow linearly with replica count.

Deliberately separate from :mod:`paddle_tpu.distributed.mesh`'s global
training mesh: a serving process may host several replica meshes at once,
and sharding the serving pool must never re-shard training state.

The "active serving mesh" is a trace-time, thread-local context: the
engine's fused-step closure enters it around the model call, and
``models/gpt.py``'s paged attention path consults it to decide whether to
wrap the scatter+attend body in ``shard_map`` over ``mp``.  Nothing reads
it at dispatch time — compiled programs carry their partitioning in the
jaxpr.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "build_serving_mesh", "replica_meshes", "active_mesh", "activate",
    "mp_size", "shard_model_for_serving", "shard_paged_cache",
    "replicate_to_mesh", "validate_head_sharding", "clone_model",
]


def _mesh_cls():
    from jax.sharding import Mesh

    return Mesh


def build_serving_mesh(dp: int, mp: int, devices: Optional[Sequence] = None):
    """One ``(dp, mp)`` mesh over the first ``dp*mp`` devices — the
    cluster-level bookkeeping view (benches report its geometry).  The
    engines themselves run on the per-replica submeshes from
    :func:`replica_meshes`."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    dp, mp = int(dp), int(mp)
    if dp < 1 or mp < 1:
        raise ValueError(f"mesh axes must be >= 1, got dp={dp} mp={mp}")
    if dp * mp > len(devs):
        raise ValueError(
            f"serving mesh (dp={dp}, mp={mp}) needs {dp * mp} devices, "
            f"have {len(devs)}")
    arr = np.array(devs[:dp * mp]).reshape(dp, mp)
    return _mesh_cls()(arr, ("dp", "mp"))


def replica_meshes(dp: int, mp: int,
                   devices: Optional[Sequence] = None) -> List:
    """One single-axis ``('mp',)`` mesh per dp replica, over disjoint
    device rows of the ``(dp, mp)`` geometry.  Each replica's pool,
    weights and compiled fused step live entirely on its own row — which
    is exactly why aggregate HBM and slots scale linearly with ``dp``."""
    full = build_serving_mesh(dp, mp, devices)
    rows = full.devices  # [dp, mp] ndarray
    return [_mesh_cls()(rows[i], ("mp",)) for i in range(int(dp))]


def mp_size(mesh) -> int:
    """Size of the mesh's ``mp`` axis (1 when absent)."""
    try:
        return int(dict(mesh.shape).get("mp", 1))
    except Exception:  # noqa: BLE001 — absent/odd meshes count as unsharded
        return 1


# ---------------------------------------------------------------------------
# trace-time active-mesh context (consumed by models/gpt.py)
# ---------------------------------------------------------------------------

class _ActiveMesh(threading.local):
    def __init__(self):
        self.mesh = None


_active = _ActiveMesh()


def active_mesh():
    """The serving mesh of the fused step currently being traced on this
    thread (None outside a sharded engine's trace)."""
    return _active.mesh


@contextmanager
def activate(mesh):
    """Mark ``mesh`` as the active serving mesh for the duration (no-op
    for ``None``).  The engine's fused-step closure wraps the model call
    in this so the paged attention path knows to shard_map over ``mp``."""
    prev = _active.mesh
    _active.mesh = mesh
    try:
        yield
    finally:
        _active.mesh = prev


# ---------------------------------------------------------------------------
# shard preconditions
# ---------------------------------------------------------------------------

def validate_head_sharding(num_heads: int, mp: int,
                           kernel: str = "ragged_paged_attention"):
    """Raise a typed ValueError (GL002-formatted, via
    ``analysis/codes.mesh_shard_gate_reason``) when the per-head partition
    cannot exist — BEFORE shard_map would crash on an indivisible head
    axis."""
    from ..analysis.codes import mesh_shard_gate_reason

    reason = mesh_shard_gate_reason(num_heads, mp, kernel=kernel)
    if reason is not None:
        raise ValueError(str(reason))
    return num_heads // max(int(mp), 1)


# ---------------------------------------------------------------------------
# placement: weights, pool, host inputs
# ---------------------------------------------------------------------------

def _put(t, mesh, spec_names):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(mesh, PartitionSpec(*spec_names))
    t._set_value(jax.device_put(t._value, sh))
    return t


def replicate_to_mesh(value, mesh):
    """device_put a raw array replicated across the replica mesh (host
    step inputs: token ids, the packed plan vector, sampling params)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(value, NamedSharding(mesh, PartitionSpec()))


def _serving_param_specs(model) -> dict:
    """id(param) -> PartitionSpec names for the Megatron column/row
    partition of the serving hot path.  QKV and fc1 are column-parallel
    (output features over ``mp``), the post-attention projection and fc2
    row-parallel (contraction dim over ``mp`` — GSPMD's all-reduce after
    them is the hot path's only cross-chip collective); embeddings, norms
    and biases of row-parallel layers replicate.  Supports both flagship
    GPT classes."""
    specs: dict = {}
    dec = getattr(model, "decoder", None)
    if dec is not None and hasattr(dec, "_PARAM_NAMES"):
        # stacked [L, ...] parameters (GPTStackedForPretraining)
        tp = {"qkv_w": (None, None, "mp"), "qkv_b": (None, "mp"),
              "fc1_w": (None, None, "mp"), "fc1_b": (None, "mp"),
              "proj_w": (None, "mp", None), "fc2_w": (None, "mp", None)}
        for name in dec._PARAM_NAMES:
            spec = tp.get(name)
            if spec is not None:
                specs[id(getattr(dec, name))] = spec
    body = getattr(model, "gpt", None)
    if body is not None and hasattr(body, "layers"):
        # layered GPTModel (GPTForPretraining)
        for layer in body.layers:
            for lin, col in ((layer.attn.qkv_proj, True),
                             (layer.attn.out_proj, False),
                             (layer.mlp.fc1, True),
                             (layer.mlp.fc2, False)):
                w = getattr(lin, "weight", None)
                b = getattr(lin, "bias", None)
                if w is not None:
                    specs[id(w)] = (None, "mp") if col else ("mp", None)
                if col and b is not None:
                    specs[id(b)] = ("mp",)
    return specs


def shard_model_for_serving(model, mesh):
    """Commit every parameter of ``model`` to the replica ``mesh``:
    column/row-parallel over ``mp`` for the TP-relevant weights, replicated
    for everything else.  Idempotent; mutates placements in place (the
    replica owns this model copy — see ``serving/sharded.py``)."""
    if mp_size(mesh) > 1:
        validate_head_sharding(model.config.num_heads, mp_size(mesh))
    specs = _serving_param_specs(model) if mp_size(mesh) > 1 else {}
    for p in model.parameters():
        _put(p, mesh, specs.get(id(p), ()))
    return model


def shard_paged_cache(cache, mesh):
    """Shard the paged KV pool per-head over ``mp``: the layered pool
    ``[P, H, page_size, D]`` on axis 1, the stacked pool
    ``[L, P, H, page_size, D]`` on axis 2 — per-chip pool bytes shrink to
    ``nbytes / mp``.  Records the shard count on the cache
    (``cache.mesh_shards``) for the per-chip accounting benches report."""
    mp = mp_size(mesh)
    if mp > 1:
        validate_head_sharding(cache.num_heads, mp)
    head_axis = 2 if cache.stacked else 1
    spec = [None] * (5 if cache.stacked else 4)
    if mp > 1:
        spec[head_axis] = "mp"
    buffers = [cache.k, cache.v] if cache.stacked else [*cache.k, *cache.v]
    for t in buffers:
        _put(t, mesh, tuple(spec))
    if getattr(cache, "quantized", False):
        # int8 pool: the per-(page, head) scale buffers shard on the SAME
        # head axis ([L, P, H] stacked / [P, H] layered)
        sspec = [None] * (3 if cache.stacked else 2)
        if mp > 1:
            sspec[-1] = "mp"
        sbuffers = ([cache.k_scale, cache.v_scale] if cache.stacked
                    else [*cache.k_scale, *cache.v_scale])
        for t in sbuffers:
            _put(t, mesh, tuple(sspec))
    cache.mesh_shards = mp
    return cache


# ---------------------------------------------------------------------------
# replica model cloning (dp scaling)
# ---------------------------------------------------------------------------

def clone_model(model, model_factory=None):
    """A fresh model instance with ``model``'s exact weights — each dp
    replica owns a full copy on its own submesh.  ``model_factory``
    overrides construction for model classes whose ``__init__`` takes more
    than the config."""
    if model_factory is not None:
        fresh = model_factory()
    else:
        fresh = type(model)(model.config)
    fresh.set_state_dict(model.state_dict())
    if getattr(model, "training", False):
        fresh.train()
    else:
        fresh.eval()
    return fresh
