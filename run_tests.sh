#!/bin/bash
# CPU test runner: sanitized env (no TPU site-hook), 8 virtual devices.
#
# Default: the FAST set (deselects @pytest.mark.slow — multi-minute XLA
# compiles).  Pass --all to run everything (CI budget), or any pytest args.
#
# A graph-lint gate runs first (tools/graph_lint.py --baseline on CPU —
# the bench-model programs must not grow NEW findings; the explicit
# --targets list includes the v3 `mesh` target, so the SPMD comm passes
# (GL008-GL011: unoverlapped collectives, replication blowup, payload
# misalignment, degenerate collectives) gate every run too; see
# docs/graph_lint.md "v3").  PADDLE_TPU_SKIP_LINT_GATE=1 skips it.
# Exit codes are unchanged: 0 clean/baselined, 1 new findings, 2 error.
#
# A checkpoint crash-injection gate runs next (tools/crash_gate.py —
# a writer killed at any pipeline stage must never corrupt latest(); see
# docs/checkpointing.md).  PADDLE_TPU_SKIP_CRASH_GATE=1 skips it.
#
# A serving gate runs third (tools/serving_bench.py --gate — continuous
# batching must stay retrace-free, match single-shot generate(), and keep
# block accounting sound under pool backpressure; it also runs the
# speculative scenario: greedy speculative output token-for-token equal
# to the non-speculative engine and generate(), a same-model draft at
# acceptance rate 1.0, randomized fault schedules draining BOTH pools —
# incl. the speculative-reservation ledger — to zero, and fused trace
# counts bounded at <= 2 target + <= 2 draft; on this 4+-device host it
# also runs the sharded scenario: a (dp=2, mp=2) ShardedServingEngine
# must reproduce generate() token-for-token through the placement layer
# with exact page accounting on every replica; see docs/serving.md
# "Sharded serving" and "Speculative decoding & multi-tenant LoRA").
# PADDLE_TPU_SKIP_SERVING_GATE=1 skips it.
#
# A serving fault-containment gate runs fourth (tools/serving_fault_gate.py
# — injected step crashes/stalls/NaN logits/pool exhaustion must fail only
# the implicated requests, keep page accounting exact, and preserve greedy
# parity for every survivor; see docs/serving.md "Failure model & SLOs").
# PADDLE_TPU_SKIP_FAULT_GATE=1 skips it.
#
# An autotune-table replay gate runs fifth (tools/autotune.py --validate —
# every committed entry must be legal under the CURRENT static tile/VMEM
# gates; pure static analysis, never times; see docs/graph_lint.md
# "v2: autotuner").  PADDLE_TPU_SKIP_AUTOTUNE_GATE=1 skips it.
#
# A telemetry gate runs sixth (tools/obs_gate.py — disabled-path span
# overhead <3% of a compiled dispatch, Chrome-trace export valid with
# nested serving-phase spans, Prometheus exposition parses; see
# docs/observability.md).  PADDLE_TPU_SKIP_OBS_GATE=1 skips it.
#
# A train-perf gate runs seventh (tools/train_perf_gate.py — the fused
# train step must stay ONE program with one dispatch per step, GL004-clean
# donation over params/moments/masters, an accounting-exact device input
# pipeline, and CPU tokens/sec above the recorded floor; see
# docs/training_perf.md).  PADDLE_TPU_SKIP_TRAIN_PERF_GATE=1 skips it.
#
# A distributed fault-tolerance gate runs eighth (tools/dist_fault_gate.py
# — real multi-process scenarios: kill-a-rank mid-collective must raise a
# typed PeerLostError within 2x the detector TTL, a restarted rank must
# never consume a prior generation's store keys, randomized store-outage
# storms must be absorbed by the bounded retry, and kill -> elastic
# restart -> resume must be bitwise-equal to the uninterrupted run; see
# docs/distributed_faults.md).  PADDLE_TPU_SKIP_DIST_FAULT_GATE=1 skips it.
#
# An elastic-serving gate runs ninth (tools/elastic_gate.py — scripted
# load through the SLO-driven controller: scale-up on a load spike,
# scale-down on idle with a BITWISE token-prefix drain, replica-kill
# re-homing with exactly-once streams, the brownout ladder engaging in
# order and releasing LIFO with every actuator restored, and anti-flap
# under adversarial oscillation; see docs/serving.md "Elasticity &
# degradation ladder").  PADDLE_TPU_SKIP_ELASTIC_GATE=1 skips it.
#
# A disaggregated-serving gate runs tenth (tools/disagg_gate.py —
# prefill/decode role parity vs the colocated cluster and the oracle,
# mid-transfer kills in BOTH directions with exact page audits on both
# pools, and independent per-role elastic scaling under a long-prompt
# spike; see docs/serving.md "Disaggregated prefill/decode").
# PADDLE_TPU_SKIP_DISAGG_GATE=1 skips it.
export JAX_PLATFORMS=cpu
export PYTHONPATH=$(python - << 'PY'
import os
print(os.pathsep.join([p for p in os.environ.get('PYTHONPATH','').split(os.pathsep) if p and 'axon' not in p]+['/root/repo']))
PY
)
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# NOTE: the persistent XLA compilation cache is deliberately NOT
# exported.  On jaxlib 0.4.36 executables deserialized from the disk
# cache mis-handle donation aliasing on the forced 8-device CPU host
# (garbage outputs / segfaults — see tests/conftest.py); conftest
# force-disables it for the pytest suite, and the gates run without it.
unset JAX_COMPILATION_CACHE_DIR

if [ -z "$PADDLE_TPU_SKIP_LINT_GATE" ]; then
    echo "run_tests: graph-lint gate (tools/graph_lint.py --baseline)"
    python "$(dirname "$0")/tools/graph_lint.py" --baseline \
        --targets train,decode,serve,mesh,churn || {
        rc=$?
        echo "run_tests: graph-lint gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_CRASH_GATE" ]; then
    echo "run_tests: checkpoint crash-injection gate (tools/crash_gate.py)"
    python "$(dirname "$0")/tools/crash_gate.py" || {
        rc=$?
        echo "run_tests: crash-injection gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_SERVING_GATE" ]; then
    echo "run_tests: serving gate (tools/serving_bench.py --gate)"
    python "$(dirname "$0")/tools/serving_bench.py" --gate || {
        rc=$?
        echo "run_tests: serving gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_FAULT_GATE" ]; then
    echo "run_tests: serving fault gate (tools/serving_fault_gate.py)"
    python "$(dirname "$0")/tools/serving_fault_gate.py" || {
        rc=$?
        echo "run_tests: serving fault gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_AUTOTUNE_GATE" ]; then
    echo "run_tests: autotune-table replay gate (tools/autotune.py --validate)"
    python "$(dirname "$0")/tools/autotune.py" --validate || {
        rc=$?
        echo "run_tests: autotune replay gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_OBS_GATE" ]; then
    echo "run_tests: telemetry gate (tools/obs_gate.py)"
    python "$(dirname "$0")/tools/obs_gate.py" || {
        rc=$?
        echo "run_tests: telemetry gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_TRAIN_PERF_GATE" ]; then
    echo "run_tests: train-perf gate (tools/train_perf_gate.py)"
    python "$(dirname "$0")/tools/train_perf_gate.py" || {
        rc=$?
        echo "run_tests: train-perf gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_DIST_FAULT_GATE" ]; then
    echo "run_tests: distributed fault gate (tools/dist_fault_gate.py)"
    python "$(dirname "$0")/tools/dist_fault_gate.py" || {
        rc=$?
        echo "run_tests: distributed fault gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_ELASTIC_GATE" ]; then
    echo "run_tests: elastic serving gate (tools/elastic_gate.py)"
    python "$(dirname "$0")/tools/elastic_gate.py" || {
        rc=$?
        echo "run_tests: elastic serving gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ -z "$PADDLE_TPU_SKIP_DISAGG_GATE" ]; then
    echo "run_tests: disaggregated serving gate (tools/disagg_gate.py)"
    python "$(dirname "$0")/tools/disagg_gate.py" || {
        rc=$?
        echo "run_tests: disaggregated serving gate FAILED (rc=$rc)"
        exit $rc
    }
fi

if [ "$1" = "--all" ]; then
    shift
    exec python -m pytest -m "slow or not slow" "$@"
fi
exec python -m pytest "$@"
