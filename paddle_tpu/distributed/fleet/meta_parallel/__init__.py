"""meta_parallel: hybrid-parallel wrappers (reference:
fleet/meta_parallel/)."""
from .tensor_parallel import TensorParallel  # noqa: F401
from .pipeline_parallel import PipelineLayer, PipelineParallel, LayerDesc, SharedLayerDesc  # noqa: F401
from .hybrid_optimizer import HybridParallelOptimizer  # noqa: F401
