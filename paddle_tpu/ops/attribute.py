"""Tensor attribute / introspection API (reference:
python/paddle/tensor/attribute.py — shape, rank, is_complex:62,
is_floating_point:139, is_integer:172, real/imag; framework dtype helpers
python/paddle/framework/framework.py set_default_dtype:34,
finfo/iinfo pybind.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod
from ..tensor import Tensor
from . import dispatch
from ._factory import ensure_tensor

__all__ = [
    "shape", "rank", "is_complex", "is_floating_point", "is_integer",
    "real", "imag", "conj", "angle", "broadcast_shape", "finfo", "iinfo",
    "get_default_dtype", "set_default_dtype", "set_printoptions",
    "is_tensor", "check_shape", "tolist",
]

_default_dtype = "float32"


def set_default_dtype(d):
    """Reference framework.py:34 — global dtype for float-typed creation ops."""
    global _default_dtype
    d = _dtype_mod.convert_dtype(d).name
    if d not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(f"set_default_dtype only supports float types, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype


def shape(input, name=None):  # noqa: A002
    """Shape as an int32 tensor (reference attribute.py shape — an op, not a
    python list, so it is usable inside traced programs)."""
    input = ensure_tensor(input)
    return Tensor(jnp.asarray(input.shape, dtype=jnp.int32), stop_gradient=True)


def rank(input, name=None):  # noqa: A002
    input = ensure_tensor(input)
    return Tensor(jnp.asarray(input.ndim, dtype=jnp.int32), stop_gradient=True)


def is_complex(x) -> bool:
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.complexfloating)


def is_floating_point(x) -> bool:
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.floating)


def is_integer(x) -> bool:
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.integer)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def tolist(x):
    """Nested python list of the tensor's values (reference
    tensor/manipulation.py tolist)."""
    return ensure_tensor(x).tolist()


def real(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jnp.real, x, op_name="real")


def imag(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jnp.imag, x, op_name="imag")


def conj(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jnp.conj, x, op_name="conj")


def angle(x, name=None):
    x = ensure_tensor(x)
    return dispatch.apply(jnp.angle, x, op_name="angle")


def broadcast_shape(x_shape, y_shape):
    """Static broadcast-shape computation (reference attribute-free helper)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


class _FInfo:
    def __init__(self, info):
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(getattr(info, "resolution", info.eps))
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


class _IInfo:
    def __init__(self, info):
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


def finfo(dtype):
    return _FInfo(jnp.finfo(_dtype_mod.to_jax_dtype(dtype)))


def iinfo(dtype):
    return _IInfo(jnp.iinfo(_dtype_mod.to_jax_dtype(dtype)))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed printing (reference tensor/to_string.py knobs)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def check_shape(shape):
    """Validate a shape argument (reference static check_shape): ints >= -1,
    at most one -1."""
    shape = list(shape)
    if sum(1 for s in shape if s == -1) > 1:
        raise ValueError(f"shape can contain at most one -1, got {shape}")
    for s in shape:
        if not isinstance(s, (int, np.integer)) or s < -1:
            raise ValueError(f"invalid dim {s!r} in shape {shape}")
    return shape
