"""Fused residual-add + RMSNorm as one Pallas TPU kernel.

Reference analog: the fused norm kernels under
paddle/phi/kernels/fusion/ (fused_bias_residual_layernorm /
rms_norm_kernel) that modern-LLM blocks call between attention and FFN.

TPU-native: one VMEM pass computes h = x + residual, the row-wise RMS
statistic, and the scaled output — the residual sum is never written to
HBM separately (the usual extra round-trip when XLA schedules the add
and the norm apart).  Returns BOTH the normalized output and h (the
carry the next residual needs).  Backward is XLA autodiff over the
same math via custom_vjp recompute — the fused win is the fwd HBM
traffic; bwd reuses XLA's fusion.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def np_prod(xs):
    out = 1
    for v in xs:
        out *= int(v)
    return out

__all__ = ["fused_add_rms_norm", "shape_supported"]

_BLOCK_ROWS = 256


def shape_supported(hidden: int) -> bool:
    """Lane constraint: the hidden (row) dim must tile the 128-wide
    lanes."""
    return hidden % 128 == 0


def _kernel(x_ref, r_ref, g_ref, o_ref, h_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    h = x + r
    ms = jnp.mean(h * h, axis=-1, keepdims=True)
    o = h * jax.lax.rsqrt(ms + eps) * g
    o_ref[...] = o.astype(o_ref.dtype)
    h_ref[...] = h.astype(h_ref.dtype)


def _pick_rows(rows: int, hdim: int) -> int:
    """Largest power-of-two row block that (a) divides rows, (b) stays
    inside the VMEM budget: 4 buffers of block*hdim*4B within ~8 MiB
    (the same discipline fused_adamw documents)."""
    if rows <= 0:
        return 0
    cap = max(1, (8 * 2 ** 20) // (16 * hdim))
    b = min(_BLOCK_ROWS, rows, cap)
    # round down to a power of two
    while b & (b - 1):
        b &= b - 1
    while b > 1 and rows % b:
        b //= 2
    return b


def _fwd_impl(x, r, g, eps, interpret):
    shape = x.shape
    hdim = shape[-1]
    x2 = x.reshape(-1, hdim)
    r2 = r.reshape(-1, hdim)
    rows = x2.shape[0]
    block = _pick_rows(rows, hdim)
    grid = (rows // block,)
    out, h = pl.pallas_call(
        functools.partial(_kernel, eps=float(eps)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, hdim), lambda i: (i, 0)),
            pl.BlockSpec((block, hdim), lambda i: (i, 0)),
            pl.BlockSpec((1, hdim), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, hdim), lambda i: (i, 0)),
            pl.BlockSpec((block, hdim), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, x.dtype),
            jax.ShapeDtypeStruct(x2.shape, x.dtype),
        ],
        interpret=interpret,
    )(x2, r2, g.reshape(1, hdim))
    return out.reshape(shape), h.reshape(shape)


def _reference(x, r, g, eps):
    h = (x + r).astype(jnp.float32)
    ms = jnp.mean(h * h, axis=-1, keepdims=True)
    out = h * jax.lax.rsqrt(ms + eps) * g.astype(jnp.float32)
    return out.astype(x.dtype), h.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_add_rms_norm(x, residual, weight, eps=1e-6, interpret=False):
    """(normed, h) where h = x + residual and
    normed = rms_norm(h) * weight — one fused VMEM pass on TPU, the
    plain XLA expression elsewhere/ineligible shapes."""
    out, h = _fused_fwd(x, residual, weight, eps, interpret)
    return out, h


def _fused_fwd(x, r, g, eps, interpret):
    from .flash_attention import _on_tpu

    rows = int(np_prod(x.shape[:-1]))
    eligible = (shape_supported(x.shape[-1]) and rows > 0
                and _pick_rows(rows, x.shape[-1]) >= 8)
    if (interpret or _on_tpu()) and eligible:
        return _fwd_impl(x, r, g, eps, interpret)
    return _reference(x, r, g, eps)


def _vjp_fwd(x, r, g, eps, interpret):
    out, h = _fused_fwd(x, r, g, eps, interpret)
    return (out, h), (x, r, g)


def _vjp_bwd(eps, interpret, res, cts):
    x, r, g = res
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, eps), x, r, g)
    return vjp(cts)


fused_add_rms_norm.defvjp(_vjp_fwd, _vjp_bwd)
